"""ray_tpu.workflow: durable workflows — DAGs with per-step checkpointing.

Analog of python/ray/workflow (workflow_executor.py, workflow_storage.py,
task_executor.py): `workflow.run(fn.bind(...))` executes the task graph,
persisting every step's output; `workflow.resume(workflow_id)` re-runs the
graph, skipping any step whose checkpoint exists — crash recovery restarts
only the unfinished suffix.

    @ray_tpu.remote
    def add(a, b): return a + b

    out = workflow.run(add.bind(add.bind(1, 2), 3), workflow_id="w1")  # 6
"""

from ray_tpu.workflow.api import (
    Continuation,
    FunctionNode,
    WorkflowStatus,
    continuation,
    delete,
    get_metadata,
    get_output,
    get_step_metadata,
    list_all,
    resume,
    run,
    run_async,
)

__all__ = [
    "Continuation",
    "FunctionNode",
    "WorkflowStatus",
    "continuation",
    "delete",
    "get_metadata",
    "get_output",
    "get_step_metadata",
    "list_all",
    "resume",
    "run",
    "run_async",
]
