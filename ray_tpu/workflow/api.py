"""Workflow execution + storage.

Steps are the reference's task nodes (python/ray/workflow/task_executor.py);
storage layout mirrors workflow_storage.py: one directory per workflow id,
one pickle per finished step, a JSON status/metadata file.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu._private.common import RayTpuError

DEFAULT_STORAGE = os.path.expanduser("~/ray_tpu_workflows")


class WorkflowStatus:
    RUNNING = "RUNNING"
    SUCCESSFUL = "SUCCESSFUL"
    FAILED = "FAILED"
    RESUMABLE = "RESUMABLE"


class FunctionNode:
    """Lazy task node: fn.bind(*args) (reference: dag/function_node.py).
    Args may contain other FunctionNodes."""

    def __init__(self, remote_fn, args: Tuple, kwargs: Dict):
        self.remote_fn = remote_fn
        self.args = args
        self.kwargs = kwargs

    def _upstream(self) -> List["FunctionNode"]:
        return [
            a
            for a in list(self.args) + list(self.kwargs.values())
            if isinstance(a, FunctionNode)
        ]


class _Storage:
    def __init__(self, workflow_id: str, base: Optional[str] = None):
        self.dir = os.path.join(base or DEFAULT_STORAGE, workflow_id)
        os.makedirs(os.path.join(self.dir, "steps"), exist_ok=True)

    def step_path(self, step_id: str) -> str:
        return os.path.join(self.dir, "steps", f"{step_id}.pkl")

    def has_step(self, step_id: str) -> bool:
        return os.path.exists(self.step_path(step_id))

    def load_step(self, step_id: str) -> Any:
        with open(self.step_path(step_id), "rb") as f:
            return pickle.load(f)

    def save_step(self, step_id: str, value: Any) -> None:
        tmp = self.step_path(step_id) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, self.step_path(step_id))  # atomic checkpoint commit

    def write_meta(self, **kw) -> None:
        meta = self.read_meta()
        meta.update(kw)
        tmp = os.path.join(self.dir, "meta.json.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(self.dir, "meta.json"))

    def read_meta(self) -> Dict[str, Any]:
        try:
            with open(os.path.join(self.dir, "meta.json")) as f:
                return json.load(f)
        except FileNotFoundError:
            return {}


def _graph_blob(node: FunctionNode) -> bytes:
    import cloudpickle

    return cloudpickle.dumps(node)


def _step_ids(node: FunctionNode) -> Dict[int, str]:
    """Deterministic step ids: topo index + function name + arg structure
    hash, so resume matches steps across processes."""
    order: List[FunctionNode] = []
    seen = set()

    def visit(n: FunctionNode):
        if id(n) in seen:
            return
        seen.add(id(n))
        for up in n._upstream():
            visit(up)
        order.append(n)

    visit(node)
    ids: Dict[int, str] = {}
    for i, n in enumerate(order):
        name = getattr(n.remote_fn, "__name__", "step")
        sig = hashlib.sha1(
            f"{i}:{name}:{len(n.args)}:{sorted(n.kwargs)}".encode()
        ).hexdigest()[:8]
        ids[id(n)] = f"{i:04d}_{name}_{sig}"
    return ids


def _execute(node: FunctionNode, storage: _Storage) -> Any:
    ids = _step_ids(node)
    cache: Dict[int, Any] = {}
    order: List[FunctionNode] = []
    seen = set()

    def visit(n: FunctionNode):
        if id(n) in seen:
            return
        seen.add(id(n))
        for up in n._upstream():
            visit(up)
        order.append(n)

    visit(node)

    for n in order:
        step_id = ids[id(n)]
        if storage.has_step(step_id):
            cache[id(n)] = storage.load_step(step_id)
            continue

        def resolve(v):
            return cache[id(v)] if isinstance(v, FunctionNode) else v

        args = [resolve(a) for a in n.args]
        kwargs = {k: resolve(v) for k, v in n.kwargs.items()}
        result = ray_tpu.get(n.remote_fn.remote(*args, **kwargs))
        storage.save_step(step_id, result)
        cache[id(n)] = result
    return cache[id(node)]


def run(
    node: FunctionNode,
    *,
    workflow_id: Optional[str] = None,
    storage: Optional[str] = None,
) -> Any:
    """Execute the workflow to completion, checkpointing each step."""
    if not isinstance(node, FunctionNode):
        raise RayTpuError("workflow.run expects fn.bind(...) (a FunctionNode)")
    workflow_id = workflow_id or f"wf-{uuid.uuid4().hex[:10]}"
    st = _Storage(workflow_id, storage)
    st.write_meta(
        workflow_id=workflow_id,
        status=WorkflowStatus.RUNNING,
        start_time=time.time(),
    )
    with open(os.path.join(st.dir, "graph.pkl"), "wb") as f:
        f.write(_graph_blob(node))
    try:
        result = _execute(node, st)
    except Exception as e:
        st.write_meta(status=WorkflowStatus.FAILED, error=repr(e))
        raise
    st.save_step("__output__", result)
    st.write_meta(status=WorkflowStatus.SUCCESSFUL, end_time=time.time())
    return result


def run_async(node: FunctionNode, **kw):
    """Run in a background task; returns an ObjectRef-like future via a
    driver thread (workflows are driver-side orchestrations)."""
    import concurrent.futures

    pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
    return pool.submit(run, node, **kw)


def resume(workflow_id: str, *, storage: Optional[str] = None) -> Any:
    """Re-run a workflow, skipping checkpointed steps."""
    st = _Storage(workflow_id, storage)
    if st.has_step("__output__"):
        return st.load_step("__output__")
    graph_path = os.path.join(st.dir, "graph.pkl")
    if not os.path.exists(graph_path):
        raise RayTpuError(f"no stored graph for workflow {workflow_id!r}")
    with open(graph_path, "rb") as f:
        node = pickle.load(f)
    st.write_meta(status=WorkflowStatus.RUNNING)
    try:
        result = _execute(node, st)
    except Exception as e:
        st.write_meta(status=WorkflowStatus.FAILED, error=repr(e))
        raise
    st.save_step("__output__", result)
    st.write_meta(status=WorkflowStatus.SUCCESSFUL, end_time=time.time())
    return result


def get_output(workflow_id: str, *, storage: Optional[str] = None) -> Any:
    st = _Storage(workflow_id, storage)
    if not st.has_step("__output__"):
        raise RayTpuError(f"workflow {workflow_id!r} has no output yet")
    return st.load_step("__output__")


def get_metadata(workflow_id: str, *, storage: Optional[str] = None) -> Dict:
    return _Storage(workflow_id, storage).read_meta()


def list_all(storage: Optional[str] = None) -> List[Tuple[str, str]]:
    base = storage or DEFAULT_STORAGE
    out = []
    if not os.path.isdir(base):
        return out
    for wid in sorted(os.listdir(base)):
        if not os.path.isdir(os.path.join(base, wid)):
            continue
        meta = _Storage(wid, base).read_meta()
        if meta:
            out.append((wid, meta.get("status", "UNKNOWN")))
    return out


def delete(workflow_id: str, *, storage: Optional[str] = None) -> None:
    import shutil

    st = _Storage(workflow_id, storage)
    shutil.rmtree(st.dir, ignore_errors=True)
