"""Workflow execution + storage.

Steps are the reference's task nodes (python/ray/workflow/task_executor.py);
storage layout mirrors workflow_storage.py: one directory per workflow id,
one pickle per finished step, a JSON status/metadata file.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu._private.common import RayTpuError

DEFAULT_STORAGE = os.path.expanduser("~/ray_tpu_workflows")


class WorkflowStatus:
    RUNNING = "RUNNING"
    SUCCESSFUL = "SUCCESSFUL"
    FAILED = "FAILED"
    RESUMABLE = "RESUMABLE"


class FunctionNode:
    """Lazy task node: fn.bind(*args) (reference: dag/function_node.py).
    Args may contain other FunctionNodes."""

    # Step-level execution options (reference: workflow.options()):
    # retries re-run the step on application exceptions; catch_exceptions
    # makes the step's value a (result, exception) pair instead of
    # propagating. CLASS-level defaults so graph.pkl files persisted
    # before these options existed still unpickle and resume.
    max_retries = 0
    catch_exceptions = False

    def __init__(self, remote_fn, args: Tuple, kwargs: Dict):
        self.remote_fn = remote_fn
        self.args = args
        self.kwargs = kwargs

    def options(
        self,
        *,
        max_retries: Optional[int] = None,
        catch_exceptions: Optional[bool] = None,
    ) -> "FunctionNode":
        if max_retries is not None:
            self.max_retries = int(max_retries)
        if catch_exceptions is not None:
            self.catch_exceptions = bool(catch_exceptions)
        return self

    def _upstream(self) -> List["FunctionNode"]:
        return [
            a
            for a in list(self.args) + list(self.kwargs.values())
            if isinstance(a, FunctionNode)
        ]


class Continuation:
    """A step's result that says "my real value is this sub-workflow"
    (reference: workflow continuations — task_executor.py re-enters the
    executor with the returned DAG). Return `workflow.continuation(
    next_step.bind(...))` from inside a step; the executor runs the
    sub-DAG (checkpointed under the parent step's namespace) and uses its
    output as the step's value. Recursion-friendly: each nesting level
    gets its own namespaced steps, so resume lands mid-recursion."""

    def __init__(self, node: FunctionNode):
        if not isinstance(node, FunctionNode):
            raise RayTpuError("continuation() expects fn.bind(...)")
        self.node = node


def continuation(node: FunctionNode) -> Continuation:
    return Continuation(node)


class _Storage:
    def __init__(self, workflow_id: str, base: Optional[str] = None):
        self.dir = os.path.join(base or DEFAULT_STORAGE, workflow_id)
        os.makedirs(os.path.join(self.dir, "steps"), exist_ok=True)

    def step_path(self, step_id: str) -> str:
        return os.path.join(self.dir, "steps", f"{step_id}.pkl")

    def has_step(self, step_id: str) -> bool:
        return os.path.exists(self.step_path(step_id))

    def load_step(self, step_id: str) -> Any:
        with open(self.step_path(step_id), "rb") as f:
            return pickle.load(f)

    def save_step(self, step_id: str, value: Any) -> None:
        tmp = self.step_path(step_id) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, self.step_path(step_id))  # atomic checkpoint commit

    def write_meta(self, **kw) -> None:
        meta = self.read_meta()
        meta.update(kw)
        tmp = os.path.join(self.dir, "meta.json.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(self.dir, "meta.json"))

    # Per-step metadata (reference: workflow_storage.py step metadata
    # records): attempts, timing, status — queryable per step.

    def _step_meta_path(self, step_id: str) -> str:
        return os.path.join(self.dir, "steps", f"{step_id}.meta.json")

    def write_step_meta(self, step_id: str, **kw) -> None:
        meta = self.read_step_meta(step_id)
        meta.update(kw)
        tmp = self._step_meta_path(step_id) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, self._step_meta_path(step_id))

    def read_step_meta(self, step_id: str) -> Dict[str, Any]:
        try:
            with open(self._step_meta_path(step_id)) as f:
                return json.load(f)
        except FileNotFoundError:
            return {}

    def list_step_meta(self) -> Dict[str, Dict[str, Any]]:
        out = {}
        steps_dir = os.path.join(self.dir, "steps")
        for fname in sorted(os.listdir(steps_dir)):
            if fname.endswith(".meta.json"):
                sid = fname[: -len(".meta.json")]
                out[sid] = self.read_step_meta(sid)
        return out

    def read_meta(self) -> Dict[str, Any]:
        try:
            with open(os.path.join(self.dir, "meta.json")) as f:
                return json.load(f)
        except FileNotFoundError:
            return {}


def _graph_blob(node: FunctionNode) -> bytes:
    import cloudpickle

    return cloudpickle.dumps(node)


def _step_ids(node: FunctionNode) -> Dict[int, str]:
    """Deterministic step ids: topo index + function name + arg structure
    hash, so resume matches steps across processes."""
    order: List[FunctionNode] = []
    seen = set()

    def visit(n: FunctionNode):
        if id(n) in seen:
            return
        seen.add(id(n))
        for up in n._upstream():
            visit(up)
        order.append(n)

    visit(node)
    ids: Dict[int, str] = {}
    for i, n in enumerate(order):
        name = getattr(n.remote_fn, "__name__", "step")
        sig = hashlib.sha1(
            f"{i}:{name}:{len(n.args)}:{sorted(n.kwargs)}".encode()
        ).hexdigest()[:8]
        ids[id(n)] = f"{i:04d}_{name}_{sig}"
    return ids


def _run_step(n: FunctionNode, step_id: str, args, kwargs, storage: _Storage) -> Any:
    """One step with retries / catch_exceptions / continuation handling
    (reference: task_executor.py — application retries + the continuation
    re-entry into the executor)."""
    storage.write_step_meta(
        step_id,
        name=getattr(n.remote_fn, "__name__", "step"),
        status="RUNNING",
        start_time=time.time(),
    )
    root = n
    root_step_id = step_id
    attempts = 0
    chain_depth = 0
    caught: Optional[Exception] = None
    result: Any = None
    while True:
        attempts += 1
        try:
            result = ray_tpu.get(n.remote_fn.remote(*args, **kwargs))
            # Continuations (the step's real value is another workflow).
            # A chain of single-step continuations — the recursion pattern
            # (e.g. fact(n) -> fact(n-1)) — iterates IN THIS FRAME: each
            # link gets its own metadata record under the root step's
            # namespace, and no threads/pools/stack accumulate with depth.
            # A continuation that is a full DAG re-enters the executor.
            # Failures at any link honor the ROOT step's
            # max_retries/catch_exceptions (checkpointed sub-steps skip on
            # retry).
            while isinstance(result, Continuation):
                sub = result.node
                if sub._upstream():
                    result = _execute(
                        sub, storage, prefix=f"{root_step_id}."
                    )
                else:
                    chain_depth += 1
                    n = sub
                    args = list(sub.args)
                    kwargs = dict(sub.kwargs)
                    step_id = (
                        f"{root_step_id}."
                        f"{chain_depth:04d}_"
                        f"{getattr(sub.remote_fn, '__name__', 'step')}"
                    )
                    storage.write_step_meta(
                        step_id,
                        name=getattr(sub.remote_fn, "__name__", "step"),
                        status="RUNNING",
                        start_time=time.time(),
                    )
                    result = ray_tpu.get(n.remote_fn.remote(*args, **kwargs))
                    storage.write_step_meta(
                        step_id, status="SUCCESSFUL", end_time=time.time()
                    )
            break
        except Exception as e:
            if attempts <= root.max_retries:
                storage.write_step_meta(
                    root_step_id, attempts=attempts, last_error=repr(e)
                )
                continue
            if root.catch_exceptions:
                caught = e
                break
            storage.write_step_meta(
                root_step_id, status="FAILED", attempts=attempts,
                last_error=repr(e), end_time=time.time(),
            )
            raise
    if root.catch_exceptions:
        result = (None, caught) if caught is not None else (result, None)
    storage.save_step(root_step_id, result)
    storage.write_step_meta(
        root_step_id,
        # A caught permanent failure must be distinguishable from a clean
        # success in the step records.
        status="CAUGHT_FAILURE" if caught is not None else "SUCCESSFUL",
        attempts=attempts,
        end_time=time.time(),
        **({"last_error": repr(caught)} if caught is not None else {}),
    )
    return result


def _execute(node: FunctionNode, storage: _Storage, prefix: str = "") -> Any:
    """Dependency-resolved parallel executor: a step is submitted the
    moment its own upstreams finish — no wave barrier, so a slow branch
    never delays ready work on an independent branch (reference:
    workflow_executor.py submits tasks as dependencies resolve). Each
    finished step is checkpointed before its value feeds downstream. On a
    step failure, in-flight siblings are drained (never orphaned into the
    storage directory) before the error propagates."""
    ids = {k: prefix + v for k, v in _step_ids(node).items()}
    cache: Dict[int, Any] = {}
    order: List[FunctionNode] = []
    seen = set()

    def visit(n: FunctionNode):
        if id(n) in seen:
            return
        seen.add(id(n))
        for up in n._upstream():
            visit(up)
        order.append(n)

    visit(node)

    import concurrent.futures as cf

    remaining: Dict[int, FunctionNode] = {}
    for n in order:
        step_id = ids[id(n)]
        if storage.has_step(step_id):
            cache[id(n)] = storage.load_step(step_id)
        else:
            remaining[id(n)] = n

    def resolve(v):
        return cache[id(v)] if isinstance(v, FunctionNode) else v

    pool = cf.ThreadPoolExecutor(max_workers=8)
    futs: Dict[Any, int] = {}  # Future -> node id
    try:
        def submit_ready():
            for nid, n in list(remaining.items()):
                if all(id(up) in cache for up in n._upstream()):
                    del remaining[nid]
                    args = [resolve(a) for a in n.args]
                    kwargs = {k: resolve(v) for k, v in n.kwargs.items()}
                    futs[
                        pool.submit(_run_step, n, ids[nid], args, kwargs, storage)
                    ] = nid

        submit_ready()
        first_error: Optional[BaseException] = None
        while futs:
            done, _pending = cf.wait(futs, return_when=cf.FIRST_COMPLETED)
            for f in done:
                nid = futs.pop(f)
                try:
                    cache[nid] = f.result()
                except BaseException as e:  # noqa: BLE001
                    if first_error is None:
                        first_error = e
            if first_error is not None:
                # Drain in-flight siblings so no thread keeps executing
                # remote tasks or writing checkpoints after run() raised.
                for f in cf.as_completed(list(futs)):
                    try:
                        f.result()
                    except BaseException:  # noqa: BLE001 - already failing
                        pass
                raise first_error
            submit_ready()
        if remaining:
            raise RayTpuError("workflow graph has a dependency cycle")
    finally:
        pool.shutdown(wait=True)
    return cache[id(node)]


def run(
    node: FunctionNode,
    *,
    workflow_id: Optional[str] = None,
    storage: Optional[str] = None,
) -> Any:
    """Execute the workflow to completion, checkpointing each step."""
    if not isinstance(node, FunctionNode):
        raise RayTpuError("workflow.run expects fn.bind(...) (a FunctionNode)")
    workflow_id = workflow_id or f"wf-{uuid.uuid4().hex[:10]}"
    st = _Storage(workflow_id, storage)
    st.write_meta(
        workflow_id=workflow_id,
        status=WorkflowStatus.RUNNING,
        start_time=time.time(),
    )
    with open(os.path.join(st.dir, "graph.pkl"), "wb") as f:
        f.write(_graph_blob(node))
    try:
        result = _execute(node, st)
    except Exception as e:
        st.write_meta(status=WorkflowStatus.FAILED, error=repr(e))
        raise
    st.save_step("__output__", result)
    st.write_meta(status=WorkflowStatus.SUCCESSFUL, end_time=time.time())
    return result


def run_async(node: FunctionNode, **kw):
    """Run in a background task; returns an ObjectRef-like future via a
    driver thread (workflows are driver-side orchestrations)."""
    import concurrent.futures

    pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
    return pool.submit(run, node, **kw)


def resume(workflow_id: str, *, storage: Optional[str] = None) -> Any:
    """Re-run a workflow, skipping checkpointed steps."""
    st = _Storage(workflow_id, storage)
    if st.has_step("__output__"):
        return st.load_step("__output__")
    graph_path = os.path.join(st.dir, "graph.pkl")
    if not os.path.exists(graph_path):
        raise RayTpuError(f"no stored graph for workflow {workflow_id!r}")
    with open(graph_path, "rb") as f:
        node = pickle.load(f)
    st.write_meta(status=WorkflowStatus.RUNNING)
    try:
        result = _execute(node, st)
    except Exception as e:
        st.write_meta(status=WorkflowStatus.FAILED, error=repr(e))
        raise
    st.save_step("__output__", result)
    st.write_meta(status=WorkflowStatus.SUCCESSFUL, end_time=time.time())
    return result


def get_output(workflow_id: str, *, storage: Optional[str] = None) -> Any:
    st = _Storage(workflow_id, storage)
    if not st.has_step("__output__"):
        raise RayTpuError(f"workflow {workflow_id!r} has no output yet")
    return st.load_step("__output__")


def get_metadata(workflow_id: str, *, storage: Optional[str] = None) -> Dict:
    return _Storage(workflow_id, storage).read_meta()


def get_step_metadata(
    workflow_id: str, *, storage: Optional[str] = None
) -> Dict[str, Dict]:
    """Per-step records: {step_id: {name, status, attempts, start/end_time,
    last_error?}} (reference: workflow_storage.py step metadata)."""
    return _Storage(workflow_id, storage).list_step_meta()


def list_all(storage: Optional[str] = None) -> List[Tuple[str, str]]:
    base = storage or DEFAULT_STORAGE
    out = []
    if not os.path.isdir(base):
        return out
    for wid in sorted(os.listdir(base)):
        if not os.path.isdir(os.path.join(base, wid)):
            continue
        meta = _Storage(wid, base).read_meta()
        if meta:
            out.append((wid, meta.get("status", "UNKNOWN")))
    return out


def delete(workflow_id: str, *, storage: Optional[str] = None) -> None:
    import shutil

    st = _Storage(workflow_id, storage)
    shutil.rmtree(st.dir, ignore_errors=True)
