"""BackendExecutor: gang bring-up + training drive loop (reference:
python/ray/train/_internal/backend_executor.py:66 — _create_placement_group
:206, start_training :436, get_next_results :559).

TPU failure model: any worker death invalidates the whole gang (a pod slice is
all-or-nothing), so recovery tears down and re-creates the entire WorkerGroup
and resumes from the latest checkpoint — per SURVEY.md §7, not the reference's
per-worker restart.
"""

from __future__ import annotations

import logging
import uuid
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train._session import TrialInfo
from ray_tpu.train._worker_group import WorkerGroup

logger = logging.getLogger(__name__)


class Backend:
    """Framework-specific gang hooks (reference: train/backend.py Backend)."""

    def on_start(self, worker_group: WorkerGroup, backend_config) -> None:
        pass

    def on_training_start(self, worker_group: WorkerGroup, backend_config) -> None:
        pass

    def on_shutdown(self, worker_group: WorkerGroup, backend_config) -> None:
        pass


@dataclass
class BackendConfig:
    @property
    def backend_cls(self):
        return Backend


@dataclass
class JaxConfig(BackendConfig):
    """JAX gang bootstrap (the analog of _TorchBackend's process-group setup,
    reference train/torch/config.py:65-147 — but collectives lower to XLA ops
    over ICI instead of NCCL).

    collective_backend:
      "xla"   — jax.distributed.initialize via GCS-KV rendezvous; one global
                Mesh spans all hosts (real TPU pods).
      "store" — named-actor store collectives (CPU fallback / CI).
      None    — no cross-worker collective group (single worker, or the user
                brings their own).
    """

    collective_backend: Optional[str] = "store"

    @property
    def backend_cls(self):
        return _JaxBackend


class _JaxBackend(Backend):
    def on_start(self, worker_group: WorkerGroup, backend_config: JaxConfig):
        be = backend_config.collective_backend
        if be is None or len(worker_group) <= 1:
            return
        group_name = f"train_{uuid.uuid4().hex[:8]}"
        self.group_name = group_name
        worker_group._collective_group = group_name
        refs = [
            w.init_collective.remote(len(worker_group), rank, be, group_name)
            for rank, w in enumerate(worker_group.workers)
        ]
        ray_tpu.get(refs)

    def on_shutdown(self, worker_group: WorkerGroup, backend_config: JaxConfig):
        name = getattr(worker_group, "_collective_group", None)
        if name:
            try:
                worker_group.execute("shutdown_collective", name)
            except Exception:
                pass


class TrainingFailedError(RuntimeError):
    pass


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: ScalingConfig,
        trial_info: TrialInfo,
        worker_env: Optional[Dict[str, str]] = None,
    ):
        self._backend_config = backend_config
        self._backend: Backend = backend_config.backend_cls()
        self._scaling = scaling_config
        self._trial_info = trial_info
        self._worker_env = worker_env
        self.worker_group: Optional[WorkerGroup] = None

    def start(self) -> None:
        self.worker_group = WorkerGroup(
            self._scaling.num_workers,
            self._scaling.as_placement_group_bundles(),
            self._scaling.placement_strategy,
            worker_env=self._worker_env,
        )
        self._backend.on_start(self.worker_group, self._backend_config)

    def start_training(
        self,
        train_fn: Callable,
        loop_config: Dict[str, Any],
        dataset_shards_per_rank: List[Dict[str, Any]],
        latest_checkpoint_path: Optional[str],
    ) -> None:
        wg = self.worker_group
        assert wg is not None, "start() must run first"
        group = getattr(wg, "_collective_group", None)
        setup_refs = []
        for rank, w in enumerate(wg.workers):
            setup_refs.append(
                w.setup_session.remote(
                    world_rank=rank,
                    world_size=len(wg),
                    local_rank=wg.local_ranks[rank],
                    local_world_size=wg.local_world_sizes[rank],
                    node_rank=wg.node_ranks[rank],
                    trial_info=self._trial_info,
                    latest_checkpoint_path=latest_checkpoint_path,
                    dataset_shards=dataset_shards_per_rank[rank],
                    loop_config=loop_config,
                    collective_group=group,
                )
            )
        ray_tpu.get(setup_refs)
        self._backend.on_training_start(wg, self._backend_config)
        blob = cloudpickle.dumps(train_fn)
        self._run_refs = [w.run.remote(blob) for w in wg.workers]

    def get_next_results(self, timeout_per_poll: float = 10.0):
        """One TrainingResult per rank, or None once all ranks finished.

        Raises TrainingFailedError if ranks disagree (some reported, some
        finished) — same consistency check as the reference (:559).
        """
        wg = self.worker_group
        assert wg is not None
        results: List[Optional[dict]] = [None] * len(wg)
        done: List[bool] = [False] * len(wg)
        while True:
            pending_idx = [
                i for i in range(len(wg)) if results[i] is None and not done[i]
            ]
            if not pending_idx:
                break
            refs = [
                wg.workers[i].poll.remote(timeout_per_poll) for i in pending_idx
            ]
            replies = ray_tpu.get(refs)
            for i, rep in zip(pending_idx, replies):
                if "result" in rep:
                    results[i] = rep["result"]
                elif rep.get("done"):
                    done[i] = True
                    if rep.get("error"):
                        raise TrainingFailedError(
                            f"rank {i} failed: {rep['error']}"
                        )
        if all(done):
            return None
        if any(done):
            raise TrainingFailedError(
                "ranks out of sync: some workers finished while others "
                "reported a result (mismatched session.report calls)"
            )
        return results

    def finish_training(self) -> List[Optional[str]]:
        """Join run() on all ranks; returns per-rank traceback strings."""
        return ray_tpu.get(self._run_refs)

    def shutdown(self) -> None:
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(self.worker_group, self._backend_config)
            except Exception:
                pass
            self.worker_group.shutdown()
            self.worker_group = None
