"""Per-worker train session (reference: python/ray/train/_internal/session.py:110).

The user's train_fn runs on an executor thread inside a TrainWorker actor; the
session is thread-local-ish process state. `report()` persists any checkpoint
directly from the worker (rank-local upload, reference: storage.py:505) and
enqueues a TrainingResult that the driver drains via the actor's `poll()`
method — the actor runs with max_concurrency > 1 so polling and training
overlap (the reference gets the same overlap from its result queue + thread).
"""

from __future__ import annotations

import os
import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu.train._checkpoint import Checkpoint, _parse_uri


@dataclass
class TrialInfo:
    name: str = "train"
    experiment_name: str = "train"
    trial_id: str = ""
    storage_path: Optional[str] = None
    trial_dir: Optional[str] = None  # {storage_path}/{experiment}/{trial}


@dataclass
class TrainingResult:
    metrics: Dict[str, Any]
    checkpoint_path: Optional[str] = None
    iteration: int = 0
    world_rank: int = 0


class TrainContext:
    """What `ray_tpu.train.get_context()` returns (reference:
    python/ray/train/context.py)."""

    def __init__(self, session: "_TrainSession"):
        self._s = session

    def get_world_size(self) -> int:
        return self._s.world_size

    def get_world_rank(self) -> int:
        return self._s.world_rank

    def get_local_rank(self) -> int:
        return self._s.local_rank

    def get_local_world_size(self) -> int:
        return self._s.local_world_size

    def get_node_rank(self) -> int:
        return self._s.node_rank

    def get_trial_name(self) -> str:
        return self._s.trial_info.name

    def get_trial_id(self) -> str:
        return self._s.trial_info.trial_id

    def get_experiment_name(self) -> str:
        return self._s.trial_info.experiment_name

    def get_trial_dir(self) -> Optional[str]:
        return self._s.trial_info.trial_dir

    def get_collective_group(self) -> Optional[str]:
        """Name of the collective group spanning the worker gang (TPU-native:
        cross-host grad sync goes through ray_tpu.util.collective on it)."""
        return self._s.collective_group


@dataclass
class _TrainSession:
    world_rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    local_world_size: int = 1
    node_rank: int = 0
    trial_info: TrialInfo = field(default_factory=TrialInfo)
    latest_checkpoint: Optional[Checkpoint] = None
    dataset_shards: Dict[str, Any] = field(default_factory=dict)
    collective_group: Optional[str] = None
    loop_config: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        self.result_queue: "queue.Queue[TrainingResult]" = queue.Queue()
        self.iteration = 0
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None

    # -- worker-side checkpoint persistence ---------------------------------

    def _persist_checkpoint(self, local_dir: str) -> str:
        """Upload `local_dir` into the trial dir; returns the persisted URI.

        All ranks may report a checkpoint; files land in the same
        checkpoint_{iter} dir (rank-local upload, reference storage.py:505).
        Rank-disambiguation is the caller's job, as in the reference.
        """
        trial_dir = self.trial_info.trial_dir
        if trial_dir is None:
            return os.path.abspath(local_dir)  # no storage: hand back in place
        dest = os.path.join(trial_dir, f"checkpoint_{self.iteration:06d}")
        fs, fs_dest = _parse_uri(dest)
        import pyarrow.fs as pafs

        fs.create_dir(fs_dest, recursive=True)
        pafs.copy_files(
            os.path.abspath(local_dir), fs_dest, destination_filesystem=fs
        )
        return dest

    # -- public session API --------------------------------------------------

    def report(
        self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None
    ) -> None:
        ckpt_path = None
        if checkpoint is not None:
            ckpt_path = self._persist_checkpoint(checkpoint.fs_path)
            self.latest_checkpoint = Checkpoint(ckpt_path)
        self.result_queue.put(
            TrainingResult(
                metrics=dict(metrics),
                checkpoint_path=ckpt_path,
                iteration=self.iteration,
                world_rank=self.world_rank,
            )
        )
        self.iteration += 1

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.latest_checkpoint

    def get_dataset_shard(self, name: str = "train"):
        if name not in self.dataset_shards:
            raise KeyError(
                f"no dataset shard named {name!r}; trainer datasets were "
                f"{sorted(self.dataset_shards)}"
            )
        return self.dataset_shards[name]


_session: Optional[_TrainSession] = None


def _set_session(s: Optional[_TrainSession]) -> None:
    global _session
    _session = s


def _get_session() -> _TrainSession:
    if _session is None:
        raise RuntimeError(
            "train session API used outside a train worker; call this from "
            "inside train_loop_per_worker"
        )
    return _session


# -- module-level API (what `ray_tpu.train` re-exports) ----------------------


def report(metrics: Dict[str, Any], *, checkpoint: Optional[Checkpoint] = None):
    _get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return _get_session().get_checkpoint()


def get_context() -> TrainContext:
    return TrainContext(_get_session())


def get_dataset_shard(name: str = "train"):
    return _get_session().get_dataset_shard(name)
