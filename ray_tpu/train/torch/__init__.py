"""TorchTrainer: distributed PyTorch training on the ray_tpu runtime.

Analog of python/ray/train/torch (torch_trainer.py:11, config.py:65-147):
the backend picks a master address/port on rank 0 and every worker joins a
torch.distributed process group (gloo — CPU/host collectives; on TPU pods
the JaxTrainer path is the native one, this trainer covers torch-based
workloads and migration parity).

    from ray_tpu.train.torch import TorchTrainer, prepare_model
    from ray_tpu.air import ScalingConfig

    def train_fn(config):
        model = prepare_model(Net())          # DDP-wrapped
        ...
        ray_tpu.train.report({"loss": loss})

    TorchTrainer(train_fn, scaling_config=ScalingConfig(num_workers=4)).fit()
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass

import cloudpickle

import ray_tpu
from ray_tpu.train._backend_executor import Backend, BackendConfig
from ray_tpu.train.base_trainer import DataParallelTrainer


@dataclass
class TorchConfig(BackendConfig):
    """reference: train/torch/config.py TorchConfig."""

    backend: str = "gloo"  # gloo (CPU) — nccl has no place on TPU hosts
    init_timeout_s: float = 120.0

    @property
    def backend_cls(self):
        return _TorchBackend


def _find_free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _setup_torch_process_group(
    backend: str, init_method: str, rank: int, world_size: int, timeout_s: float
):
    import datetime

    import torch.distributed as dist

    if dist.is_initialized():
        return
    dist.init_process_group(
        backend=backend,
        init_method=init_method,
        rank=rank,
        world_size=world_size,
        timeout=datetime.timedelta(seconds=timeout_s),
    )


def _teardown_torch_process_group():
    import torch.distributed as dist

    if dist.is_initialized():
        dist.destroy_process_group()


class _TorchBackend(Backend):
    """reference: train/torch/config.py _TorchBackend.on_start — rank 0
    picks (addr, port), every worker runs init_process_group."""

    def on_start(self, worker_group, backend_config: TorchConfig):
        if len(worker_group) <= 1:
            return
        # Rank 0's host + a free port become the rendezvous point.
        port = ray_tpu.get(
            worker_group.workers[0].apply.remote(cloudpickle.dumps(_find_free_port))
        )
        master_addr = "127.0.0.1"  # single-host gangs; TCP store binds here
        init_method = f"tcp://{master_addr}:{port}"
        setup_blob = cloudpickle.dumps(_setup_torch_process_group)
        refs = [
            w.apply.remote(
                setup_blob,
                backend_config.backend,
                init_method,
                rank,
                len(worker_group),
                backend_config.init_timeout_s,
            )
            for rank, w in enumerate(worker_group.workers)
        ]
        ray_tpu.get(refs, timeout=backend_config.init_timeout_s + 30)

    def on_shutdown(self, worker_group, backend_config: TorchConfig):
        try:
            worker_group.execute("apply", cloudpickle.dumps(_teardown_torch_process_group))
        except Exception:
            pass


class TorchTrainer(DataParallelTrainer):
    _default_backend_config = TorchConfig


# -- in-loop helpers (reference: train/torch/train_loop_utils.py) -------------


def prepare_model(model):
    """Wrap in DDP when a process group is live; move is a no-op on CPU."""
    import torch.distributed as dist
    from torch.nn.parallel import DistributedDataParallel as DDP

    if dist.is_available() and dist.is_initialized() and dist.get_world_size() > 1:
        return DDP(model)
    return model


def prepare_data_loader(data_loader):
    """Reshard a DataLoader across workers via DistributedSampler."""
    import torch.distributed as dist
    from torch.utils.data import DataLoader
    from torch.utils.data.distributed import DistributedSampler

    if not (dist.is_available() and dist.is_initialized()):
        return data_loader
    if isinstance(data_loader.sampler, DistributedSampler):
        return data_loader
    return DataLoader(
        data_loader.dataset,
        batch_size=data_loader.batch_size,
        sampler=DistributedSampler(data_loader.dataset),
        num_workers=data_loader.num_workers,
        collate_fn=data_loader.collate_fn,
        drop_last=data_loader.drop_last,
    )


__all__ = ["TorchConfig", "TorchTrainer", "prepare_data_loader", "prepare_model"]
