"""JaxTrainer — the flagship trainer (reference analog: TorchTrainer,
python/ray/train/torch/torch_trainer.py:11; TPU-native per SURVEY.md §7
step 6: one worker per TPU host, train step is one pjit/shard_map program).

    from ray_tpu.train.jax import JaxTrainer
    from ray_tpu.air import ScalingConfig

    def train_fn(config):
        mesh = ray_tpu.parallel.make_mesh(...)   # local chips of this host
        ...
        ray_tpu.train.report({"loss": loss}, checkpoint=ckpt)

    JaxTrainer(train_fn, scaling_config=ScalingConfig(num_workers=4,
               use_tpu=True)).fit()
"""

from ray_tpu.train._backend_executor import JaxConfig
from ray_tpu.train.base_trainer import DataParallelTrainer


class JaxTrainer(DataParallelTrainer):
    _default_backend_config = JaxConfig


__all__ = ["JaxTrainer", "JaxConfig"]
