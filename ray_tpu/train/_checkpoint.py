"""Checkpoint: a handle to a directory of files (reference:
python/ray/train/_checkpoint.py:56 — `Checkpoint` is a path + filesystem,
not an in-memory blob).

Local filesystems only need the path; remote URIs go through pyarrow.fs the
same way the reference routes them (train/_internal/storage.py).
"""

from __future__ import annotations

import contextlib
import os
import shutil
import tempfile
import uuid
from typing import Iterator, Optional


def _parse_uri(path: str):
    """Return (pyarrow.fs.FileSystem, fs_path) for a path or URI."""
    import pyarrow.fs as pafs

    if "://" in path:
        return pafs.FileSystem.from_uri(path)
    return pafs.LocalFileSystem(), os.path.abspath(path)


class Checkpoint:
    """Directory-of-files checkpoint handle."""

    def __init__(self, path: str, filesystem=None):
        self.path = path
        if filesystem is None:
            filesystem, self.fs_path = _parse_uri(path)
        else:
            self.fs_path = path
        self.filesystem = filesystem

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(os.path.abspath(path))

    def to_directory(self, path: Optional[str] = None) -> str:
        """Materialize the checkpoint into a local directory."""
        dest = path or os.path.join(
            tempfile.gettempdir(), f"ckpt_{uuid.uuid4().hex[:12]}"
        )
        os.makedirs(dest, exist_ok=True)
        import pyarrow.fs as pafs

        if isinstance(self.filesystem, pafs.LocalFileSystem):
            if os.path.abspath(self.fs_path) != os.path.abspath(dest):
                shutil.copytree(self.fs_path, dest, dirs_exist_ok=True)
        else:
            pafs.copy_files(
                self.fs_path, dest, source_filesystem=self.filesystem
            )
        return dest

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        """Context manager yielding a local directory view of the checkpoint."""
        import pyarrow.fs as pafs

        if isinstance(self.filesystem, pafs.LocalFileSystem):
            yield self.fs_path
        else:
            tmp = self.to_directory()
            try:
                yield tmp
            finally:
                shutil.rmtree(tmp, ignore_errors=True)

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"

    def __eq__(self, other):
        return isinstance(other, Checkpoint) and other.path == self.path

    def __hash__(self):
        return hash(self.path)
