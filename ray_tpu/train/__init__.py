"""ray_tpu.train — distributed training orchestration (reference:
python/ray/train)."""

from ray_tpu.air.config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train._backend_executor import (
    Backend,
    BackendConfig,
    JaxConfig,
    TrainingFailedError,
)
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._session import (
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
)
from ray_tpu.train.base_trainer import BaseTrainer, DataParallelTrainer

__all__ = [
    "Checkpoint",
    "CheckpointConfig",
    "FailureConfig",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "Backend",
    "BackendConfig",
    "JaxConfig",
    "TrainingFailedError",
    "BaseTrainer",
    "DataParallelTrainer",
    "report",
    "get_checkpoint",
    "get_context",
    "get_dataset_shard",
]
