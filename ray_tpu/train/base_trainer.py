"""BaseTrainer / DataParallelTrainer (reference: python/ray/train/base_trainer.py
:567 `fit`, train/data_parallel_trainer.py:428 `training_loop`).

`fit()` drives the BackendExecutor directly; under Tune the same `_run_loop`
executes inside a trial actor via `as_trainable()` (the reference couples the
two the same way: base_trainer.py:608 wraps every fit in a single-trial Tuner).
"""

from __future__ import annotations

import os
import shutil
import uuid
from typing import Any, Callable, Dict, List, Optional

from ray_tpu._private.common import RayTpuError
from ray_tpu.air.config import (
    CheckpointConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train._backend_executor import (
    BackendConfig,
    BackendExecutor,
    TrainingFailedError,
)
from ray_tpu.train._checkpoint import Checkpoint, _parse_uri
from ray_tpu.train._session import TrialInfo


class _CheckpointManager:
    """Top-K checkpoint retention (reference:
    train/_internal/checkpoint_manager.py)."""

    def __init__(self, config: CheckpointConfig):
        self.config = config
        self.checkpoints: List[tuple] = []  # (path, metrics)

    def register(self, path: str, metrics: Dict[str, Any]) -> None:
        self.checkpoints.append((path, dict(metrics)))
        k = self.config.num_to_keep
        if k is None or len(self.checkpoints) <= k:
            return
        attr = self.config.checkpoint_score_attribute
        if attr is None:
            drop = self.checkpoints.pop(0)  # FIFO: drop oldest
        else:
            sign = 1 if self.config.checkpoint_score_order == "max" else -1
            worst = min(
                range(len(self.checkpoints) - 1),  # never drop the newest
                key=lambda i: sign
                * float(self.checkpoints[i][1].get(attr, float("-inf") * sign)),
            )
            drop = self.checkpoints.pop(worst)
        self._delete(drop[0])

    @staticmethod
    def _delete(path: str) -> None:
        try:
            fs, fs_path = _parse_uri(path)
            fs.delete_dir(fs_path)
        except Exception:
            shutil.rmtree(path, ignore_errors=True)

    @property
    def latest(self) -> Optional[str]:
        return self.checkpoints[-1][0] if self.checkpoints else None

    def best(self) -> Optional[str]:
        attr = self.config.checkpoint_score_attribute
        if not self.checkpoints:
            return None
        if attr is None:
            return self.checkpoints[-1][0]
        sign = 1 if self.config.checkpoint_score_order == "max" else -1
        return max(
            self.checkpoints,
            key=lambda c: sign * float(c[1].get(attr, float("-inf") * sign)),
        )[0]


def _shard_datasets(
    datasets: Dict[str, Any], num_workers: int
) -> List[Dict[str, Any]]:
    """Split each dataset across ranks: ray_tpu.data Datasets via
    streaming_split (reference: train/_internal/data_config.py), plain
    sequences by strided slicing, everything else replicated."""
    per_rank: List[Dict[str, Any]] = [dict() for _ in range(num_workers)]
    for name, ds in (datasets or {}).items():
        if hasattr(ds, "streaming_split"):
            shards = ds.streaming_split(num_workers)
            for r in range(num_workers):
                per_rank[r][name] = shards[r]
        elif isinstance(ds, (list, tuple)):
            for r in range(num_workers):
                per_rank[r][name] = list(ds[r::num_workers])
        else:
            for r in range(num_workers):
                per_rank[r][name] = ds
    return per_rank


class BaseTrainer:
    """reference: python/ray/train/base_trainer.py BaseTrainer."""

    _default_backend_config: Callable[[], BackendConfig] = BackendConfig

    def __init__(
        self,
        *,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        raise NotImplementedError


class DataParallelTrainer(BaseTrainer):
    """reference: python/ray/train/data_parallel_trainer.py."""

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        backend_config: Optional[BackendConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        worker_env: Optional[Dict[str, str]] = None,
    ):
        super().__init__(
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint,
        )
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.backend_config = backend_config or type(self)._default_backend_config()
        self.worker_env = worker_env

    # -- experiment layout ---------------------------------------------------

    def _make_trial_info(self) -> TrialInfo:
        name = self.run_config.name or f"{type(self).__name__}_{uuid.uuid4().hex[:8]}"
        storage = self.run_config.resolved_storage_path()
        return TrialInfo(
            name=name,
            experiment_name=name,
            trial_id=uuid.uuid4().hex[:12],
            storage_path=storage,
            trial_dir=os.path.join(storage, name),
        )

    # -- the drive loop ------------------------------------------------------

    def _run_loop(
        self,
        trial_info: TrialInfo,
        report_cb: Optional[Callable[[Dict[str, Any], Optional[str]], None]] = None,
    ) -> Result:
        """Run (and re-run on gang failure) until training completes."""
        if trial_info.trial_dir:
            fs, fs_dir = _parse_uri(trial_info.trial_dir)
            fs.create_dir(fs_dir, recursive=True)
        ckpt_manager = _CheckpointManager(self.run_config.checkpoint_config)
        latest_ckpt: Optional[str] = (
            self.resume_from_checkpoint.path if self.resume_from_checkpoint else None
        )
        max_failures = self.run_config.failure_config.max_failures
        history: List[Dict[str, Any]] = []
        attempt = 0
        error: Optional[BaseException] = None

        while True:
            executor = BackendExecutor(
                self.backend_config,
                self.scaling_config,
                trial_info,
                worker_env=self.worker_env,
            )
            try:
                executor.start()
                shards = _shard_datasets(
                    self.datasets, self.scaling_config.num_workers
                )
                executor.start_training(
                    self.train_loop_per_worker,
                    self.train_loop_config,
                    shards,
                    latest_ckpt,
                )
                while True:
                    results = executor.get_next_results()
                    if results is None:
                        break
                    metrics = results[0]["metrics"]
                    ckpt = next(
                        (
                            r["checkpoint_path"]
                            for r in results
                            if r and r["checkpoint_path"]
                        ),
                        None,
                    )
                    if ckpt:
                        latest_ckpt = ckpt
                        ckpt_manager.register(ckpt, metrics)
                    history.append(metrics)
                    if report_cb is not None:
                        report_cb(metrics, ckpt)
                executor.finish_training()
                error = None
                break
            except (TrainingFailedError, RayTpuError) as e:
                error = e
                attempt += 1
                if attempt > max_failures >= 0 and max_failures != -1:
                    break
            finally:
                executor.shutdown()

        best = ckpt_manager.best() or latest_ckpt
        return Result(
            metrics=history[-1] if history else None,
            checkpoint=Checkpoint(best) if best else None,
            path=trial_info.trial_dir,
            error=error,
            metrics_history=history,
        )

    def fit(self) -> Result:
        result = self._run_loop(self._make_trial_info())
        if result.error is not None:
            raise TrainingFailedError(
                f"training failed after retries: {result.error}"
            ) from result.error
        return result

    # -- Tune integration ----------------------------------------------------

    def as_trainable(self):
        """Wrap this trainer as a Tune function-trainable (reference:
        base_trainer.py:819). The returned callable runs the full drive loop
        inside the trial and re-reports every worker report to Tune."""
        trainer = self

        def _trainable(config: Dict[str, Any]):
            import copy

            from ray_tpu import tune
            from ray_tpu.train import _session

            run_loop_config = dict(trainer.train_loop_config)
            run_loop_config.update(config.get("train_loop_config", config))
            t = copy.copy(trainer)
            t.train_loop_config = run_loop_config
            # Nest the inner worker gang's artifacts inside the tune trial's
            # directory (reference: the trainer IS the trial).
            trial_info = copy.copy(_session._get_session().trial_info)

            def cb(metrics, ckpt_path):
                tune.report(
                    metrics,
                    checkpoint=Checkpoint(ckpt_path) if ckpt_path else None,
                    _already_persisted=True,
                )

            result = t._run_loop(trial_info, report_cb=cb)
            if result.error is not None:
                raise result.error

        _trainable.__name__ = f"{type(self).__name__}_trainable"
        return _trainable
