"""TrainWorker actor + WorkerGroup (reference:
python/ray/train/_internal/worker_group.py).

One TrainWorker actor per TPU host. The actor runs with max_concurrency > 1 so
`run()` (the user's train loop, on one executor thread) and `poll()` (driver
drains results, on another) overlap.
"""

from __future__ import annotations

import os
import traceback
from typing import Any, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.train import _session
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._session import TrialInfo, _TrainSession


class TrainWorker:
    """Actor hosting one rank of the training gang."""

    def __init__(self, env: Optional[Dict[str, str]] = None):
        for k, v in (env or {}).items():
            os.environ[k] = v
        self.session: Optional[_TrainSession] = None

    def node_info(self) -> Dict[str, str]:
        from ray_tpu._private import worker as worker_mod

        core = worker_mod._core()
        return {"node_id": core.node_id, "pid": str(os.getpid())}

    def setup_session(
        self,
        *,
        world_rank: int,
        world_size: int,
        local_rank: int,
        local_world_size: int,
        node_rank: int,
        trial_info: TrialInfo,
        latest_checkpoint_path: Optional[str],
        dataset_shards: Dict[str, Any],
        loop_config: Dict[str, Any],
        collective_group: Optional[str],
        start_iteration: int = 0,
    ) -> None:
        s = _TrainSession(
            world_rank=world_rank,
            world_size=world_size,
            local_rank=local_rank,
            local_world_size=local_world_size,
            node_rank=node_rank,
            trial_info=trial_info,
            dataset_shards=dataset_shards,
            collective_group=collective_group,
            loop_config=loop_config,
        )
        if latest_checkpoint_path:
            s.latest_checkpoint = Checkpoint(latest_checkpoint_path)
        s.iteration = start_iteration
        self.session = s
        _session._set_session(s)

    def apply(self, fn_blob: bytes, *args):
        """Run an arbitrary setup function on this worker (backend hooks —
        reference: worker_group.py execute of setup callables)."""
        fn = cloudpickle.loads(fn_blob)
        return fn(*args)

    def init_collective(
        self, world_size: int, rank: int, backend: str, group_name: str
    ) -> None:
        from ray_tpu.util import collective

        if not collective.is_group_initialized(group_name):
            collective.init_collective_group(
                world_size, rank, backend=backend, group_name=group_name
            )

    def run(self, fn_blob: bytes) -> Optional[str]:
        """Execute the train loop; returns a traceback string on failure."""
        assert self.session is not None, "setup_session must run first"
        s = self.session
        try:
            # Deserialize inside the guard: an unloadable blob (missing
            # module, version skew) must still set `finished`, or the
            # driver's poll loop waits forever for a rank that never ran.
            fn = cloudpickle.loads(fn_blob)
            if s.loop_config is not None and _takes_config(fn):
                fn(s.loop_config)
            else:
                fn()
            return None
        except BaseException as e:  # noqa: BLE001 - reported to driver
            s.error = e
            return traceback.format_exc()
        finally:
            s.finished.set()

    def poll(
        self, timeout: float = 5.0, max_results: Optional[int] = 1
    ) -> Dict[str, Any]:
        """Blocking-drain of queued TrainingResults.

        max_results=1 → lock-step drain (train's per-round rank sync);
        None → drain everything queued (tune, where a fast trial may have
        reported many times between controller rounds)."""
        import queue as _q

        assert self.session is not None
        s = self.session
        out = []
        try:
            out.append(s.result_queue.get(timeout=timeout))
            while max_results is None or len(out) < max_results:
                out.append(s.result_queue.get_nowait())
        except _q.Empty:
            pass
        if out:
            results = [
                {
                    "metrics": r.metrics,
                    "checkpoint_path": r.checkpoint_path,
                    "iteration": r.iteration,
                    "world_rank": r.world_rank,
                }
                for r in out
            ]
            if max_results == 1:
                return {"result": results[0]}
            return {"results": results}
        if s.finished.is_set() and s.result_queue.empty():
            return {"done": True, "error": repr(s.error) if s.error else None}
        return {"pending": True}

    def shutdown_collective(self, group_name: str) -> None:
        from ray_tpu.util import collective

        if collective.is_group_initialized(group_name):
            collective.destroy_collective_group(group_name)


def _takes_config(fn) -> bool:
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return True
    return len(sig.parameters) >= 1


class WorkerGroup:
    """The gang of TrainWorker actors, placed one-per-bundle in a PG."""

    def __init__(
        self,
        num_workers: int,
        bundles: List[Dict[str, float]],
        placement_strategy: str,
        worker_env: Optional[Dict[str, str]] = None,
    ):
        from ray_tpu.util.placement_group import placement_group
        from ray_tpu.util.scheduling_strategies import (
            PlacementGroupSchedulingStrategy,
        )

        self.pg = placement_group(bundles, strategy=placement_strategy)
        if not self.pg.ready(timeout=120):
            raise RuntimeError(
                "placement group for the train worker gang did not become "
                f"ready (bundles={bundles})"
            )
        cls = ray_tpu.remote(TrainWorker)
        self.workers = [
            cls.options(
                max_concurrency=4,
                num_cpus=0,  # resources held via the bundle reservation
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=self.pg, placement_group_bundle_index=i
                ),
            ).remote(worker_env)
            for i in range(num_workers)
        ]
        # Rank layout: sort by node so local ranks are contiguous per host.
        infos = ray_tpu.get([w.node_info.remote() for w in self.workers])
        self.node_ids = [i["node_id"] for i in infos]
        order: Dict[str, int] = {}
        for nid in self.node_ids:
            order.setdefault(nid, len(order))
        self.node_ranks = [order[nid] for nid in self.node_ids]
        counts: Dict[str, int] = {}
        self.local_ranks = []
        for nid in self.node_ids:
            self.local_ranks.append(counts.get(nid, 0))
            counts[nid] = counts.get(nid, 0) + 1
        self.local_world_sizes = [counts[nid] for nid in self.node_ids]

    def __len__(self):
        return len(self.workers)

    def execute(self, method: str, *args, **kwargs) -> List[Any]:
        """Call `method` on every worker, blocking; returns per-rank results."""
        refs = [
            getattr(w, method).remote(*args, **kwargs) for w in self.workers
        ]
        return ray_tpu.get(refs)

    def execute_async(self, method: str, *args, **kwargs):
        return [getattr(w, method).remote(*args, **kwargs) for w in self.workers]

    def shutdown(self) -> None:
        from ray_tpu.util.placement_group import remove_placement_group

        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        try:
            remove_placement_group(self.pg)
        except Exception:
            pass
        self.workers = []
