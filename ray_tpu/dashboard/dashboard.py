"""Dashboard: aiohttp server exposing cluster state as JSON + a minimal UI.

Analog of the reference's dashboard/ (head.py:81 + modules): instead of a
React SPA it serves one self-contained HTML page over the same JSON
endpoints the state API uses — nodes, actors, jobs, tasks, serve apps.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any, Dict, Optional, Tuple

INDEX_HTML = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 2rem; color: #222; }
 h1 { font-size: 1.3rem; } h2 { font-size: 1.05rem; margin-top: 1.5rem; }
 table { border-collapse: collapse; width: 100%; font-size: .85rem; }
 th, td { border: 1px solid #ddd; padding: .3rem .5rem; text-align: left; }
 th { background: #f5f5f5; } .mono { font-family: monospace; }
</style></head>
<body>
<h1>ray_tpu dashboard</h1>
<div id="root">loading…</div>
<script>
const fmt = (o) => typeof o === 'object' ? JSON.stringify(o) : o;
function table(rows, cols) {
  if (!rows || !rows.length) return '<i>none</i>';
  cols = cols || Object.keys(rows[0]);
  let h = '<table><tr>' + cols.map(c => `<th>${c}</th>`).join('') + '</tr>';
  for (const r of rows)
    h += '<tr>' + cols.map(c => `<td class=mono>${fmt(r[c] ?? '')}</td>`).join('') + '</tr>';
  return h + '</table>';
}
async function refresh() {
  const j = async (u) => (await fetch(u)).json();
  const [nodes, actors, jobs, tasks] = await Promise.all([
    j('/api/nodes'), j('/api/actors'), j('/api/jobs'), j('/api/tasks/summary')]);
  document.getElementById('root').innerHTML =
    '<h2>Nodes</h2>' + table(nodes.nodes) +
    '<h2>Actors</h2>' + table(actors.actors,
       ['actor_id','class_name','name','state','node_id','num_restarts']) +
    '<h2>Jobs</h2>' + table(jobs.jobs) +
    '<h2>Task summary</h2><pre>' + JSON.stringify(tasks, null, 2) + '</pre>';
}
refresh(); setInterval(refresh, 5000);
</script></body></html>
"""


class Dashboard:
    def __init__(
        self,
        gcs_addr: Tuple[str, int],
        host: str = "127.0.0.1",
        port: int = 8265,
        session_name: str = "",
    ):
        self.gcs_addr = gcs_addr
        self.host = host
        self.port = port
        self.session_name = session_name
        self._conn = None
        self._runner = None
        self._sd_writer = None

    async def _gcs(self, method: str, payload: Optional[dict] = None):
        from ray_tpu._private import rpc

        if self._conn is None or self._conn.closed:
            self._conn = await rpc.connect(*self.gcs_addr)
        return await self._conn.call(method, payload or {})

    async def start(self) -> Tuple[str, int]:
        from aiohttp import web

        app = web.Application()
        app.router.add_get("/", self._index)
        app.router.add_get("/api/cluster_status", self._cluster_status)
        app.router.add_get("/api/nodes", self._nodes)
        app.router.add_get("/api/actors", self._actors)
        app.router.add_get("/api/jobs", self._jobs)
        app.router.add_get("/api/placement_groups", self._pgs)
        app.router.add_get("/api/tasks", self._tasks)
        app.router.add_get("/api/logs", self._logs)
        app.router.add_get("/api/tasks/summary", self._task_summary)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/-/healthz", self._healthz)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = site._server.sockets[0].getsockname()[1]
        # Observability side outputs (reference: metrics_agent.py:595 file-SD
        # + dashboard/modules/metrics generated Grafana dashboards): a stock
        # Prometheus file_sd_config pointed at the session dir scrapes this
        # dashboard's /metrics; the Grafana JSON is provisioning-ready.
        try:
            import os
            import tempfile

            from ray_tpu.util.metrics_export import (
                PrometheusServiceDiscoveryWriter,
                write_grafana_dashboards,
            )

            session_dir = os.path.join(
                tempfile.gettempdir(),
                f"ray_tpu_{self.session_name or 'default'}",
            )
            self._sd_writer = PrometheusServiceDiscoveryWriter(
                lambda: [f"{self.host}:{self.port}"], session_dir
            )
            self._sd_writer.start()
            write_grafana_dashboards(session_dir)
        except Exception:
            pass
        return self.host, self.port

    async def stop(self) -> None:
        if self._sd_writer is not None:
            self._sd_writer.stop()
            self._sd_writer = None
        if self._runner is not None:
            await self._runner.cleanup()
        if self._conn is not None:
            await self._conn.close()

    # -- handlers ------------------------------------------------------------

    async def _index(self, request):
        from aiohttp import web

        return web.Response(text=INDEX_HTML, content_type="text/html")

    async def _healthz(self, request):
        from aiohttp import web

        return web.Response(text="success")

    async def _cluster_status(self, request):
        from aiohttp import web

        return web.json_response(await self._gcs("GetClusterStatus"))

    async def _nodes(self, request):
        from aiohttp import web

        return web.json_response(await self._gcs("GetAllNodes"))

    async def _actors(self, request):
        from aiohttp import web

        return web.json_response(await self._gcs("ListActors"))

    async def _jobs(self, request):
        from aiohttp import web
        from ray_tpu.job.job_manager import JOB_INFO_NS

        reply = await self._gcs("KVKeys", {"ns": JOB_INFO_NS, "prefix": ""})
        jobs = []
        for key in reply.get("keys", []):
            blob = (await self._gcs("KVGet", {"ns": JOB_INFO_NS, "key": key})).get(
                "value"
            )
            if blob:
                jobs.append(json.loads(blob))
        return web.json_response({"jobs": jobs})

    async def _pgs(self, request):
        from aiohttp import web

        return web.json_response(await self._gcs("ListPlacementGroups"))

    async def _tasks(self, request):
        from aiohttp import web

        reply = await self._gcs("ListTaskEvents", {"limit": 5000})
        return web.json_response(reply)

    async def _logs(self, request):
        """Log viewer endpoint: ?node_id=&filename=&worker_id=&tail= —
        proxies the raylet GetLog/ListLogs RPCs (reference: dashboard log
        module + state API get_log)."""
        from aiohttp import web

        from ray_tpu._private import rpc

        q = request.query
        nodes = (await self._gcs("GetAllNodes"))["nodes"]
        out = {}
        for n in nodes:
            if n["state"] != "ALIVE":
                continue
            if q.get("node_id") and n["node_id"] != q["node_id"]:
                continue
            try:
                conn = await rpc.connect(*n["addr"], retry=2)
            except rpc.RpcError:
                continue
            try:
                if q.get("filename") or q.get("worker_id"):
                    try:
                        tail = min(int(q.get("tail", 1000)), 100000)
                    except ValueError:
                        return web.json_response(
                            {"error": "tail must be an integer"}, status=400
                        )
                    reply = await conn.call(
                        "GetLog",
                        {
                            "filename": q.get("filename"),
                            "worker_id": q.get("worker_id"),
                            "stream": q.get("stream", "stderr"),
                            "tail": tail,
                        },
                    )
                else:
                    reply = await conn.call("ListLogs", {})
                out[n["node_id"]] = reply
            finally:
                await conn.close()
        return web.json_response(out)

    async def _metrics(self, request):
        """Prometheus text exposition merged across all workers (the
        reference MetricsAgent role)."""
        from aiohttp import web

        from ray_tpu.util.metrics import METRICS_NS, render_prometheus

        keys = (await self._gcs("KVKeys", {"ns": METRICS_NS, "prefix": ""})).get(
            "keys", []
        )
        per_worker = {}
        for key in keys:
            blob = (await self._gcs("KVGet", {"ns": METRICS_NS, "key": key})).get(
                "value"
            )
            if blob:
                per_worker[key] = json.loads(blob)
        return web.Response(
            text=render_prometheus(per_worker),
            content_type="text/plain",
        )

    async def _task_summary(self, request):
        from aiohttp import web

        reply = await self._gcs("ListTaskEvents", {"limit": 100000})
        latest: Dict[str, dict] = {}
        for e in reply["events"]:
            if e.get("state") in ("PROFILE", "SPAN"):
                continue  # phase/trace records, not lifecycle states
            cur = latest.get(e["task_id"])
            if cur is None or e["time"] >= cur["time"]:
                latest[e["task_id"]] = e
        summary: Dict[str, Dict[str, int]] = {}
        for e in latest.values():
            name = e.get("name") or "?"
            summary.setdefault(name, {})
            summary[name][e["state"]] = summary[name].get(e["state"], 0) + 1
        return web.json_response({"summary": summary, "total": len(latest)})


async def run_dashboard(gcs_addr, host="127.0.0.1", port=8265):
    dash = Dashboard(tuple(gcs_addr), host, port)
    bound = await dash.start()
    print(f"dashboard at http://{bound[0]}:{bound[1]}")
    while True:
        await asyncio.sleep(3600)
