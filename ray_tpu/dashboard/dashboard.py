"""Dashboard: aiohttp server exposing cluster state as JSON + a SPA UI.

Analog of the reference's dashboard/ (head.py:81 + modules + the React
client under dashboard/client): a self-contained single-page app (no build
step, no CDN — it must work on air-gapped TPU pods) served over the same
JSON endpoints the state API uses — overview, nodes, actors, placement
groups, jobs, tasks, structured events, logs, and Prometheus metrics.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any, Dict, Optional, Tuple

INDEX_HTML = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<style>
 :root { --fg:#1a1d21; --muted:#667; --line:#e3e6ea; --bg:#fff;
         --accent:#2563eb; --ok:#16a34a; --warn:#d97706; --err:#dc2626; }
 body { font-family: system-ui, sans-serif; margin:0; color:var(--fg);
        background:var(--bg); }
 header { display:flex; align-items:center; gap:1.2rem; padding:.7rem 1.4rem;
          border-bottom:1px solid var(--line); }
 header b { font-size:1.05rem; }
 nav a { margin-right:.9rem; text-decoration:none; color:var(--muted);
         font-size:.92rem; padding:.25rem 0; }
 nav a.active { color:var(--accent); border-bottom:2px solid var(--accent); }
 main { padding:1rem 1.4rem; }
 h2 { font-size:1rem; margin:1.1rem 0 .5rem; }
 table { border-collapse:collapse; width:100%; font-size:.82rem; }
 th,td { border:1px solid var(--line); padding:.28rem .5rem; text-align:left;
         vertical-align:top; max-width:26rem; overflow-wrap:anywhere; }
 th { background:#f6f7f9; position:sticky; top:0; cursor:pointer; }
 .mono { font-family:ui-monospace, monospace; }
 .pill { display:inline-block; padding:.05rem .45rem; border-radius:.6rem;
         font-size:.75rem; color:#fff; }
 .ALIVE,.READY,.SUCCEEDED,.CREATED,.RUNNING_ok { background:var(--ok); }
 .PENDING,.RESTARTING,.PENDING_CREATION,.RUNNING { background:var(--warn); }
 .DEAD,.FAILED,.ERROR,.STOPPED { background:var(--err); }
 .cards { display:flex; gap:1rem; flex-wrap:wrap; margin:.6rem 0 1rem; }
 .card { border:1px solid var(--line); border-radius:.5rem;
         padding:.7rem 1.1rem; min-width:9rem; }
 .card .n { font-size:1.5rem; font-weight:600; }
 .card .l { color:var(--muted); font-size:.8rem; }
 .bar { height:.5rem; background:#eef1f4; border-radius:.3rem;
        overflow:hidden; margin-top:.3rem; }
 .bar i { display:block; height:100%; background:var(--accent); }
 input[type=search] { padding:.3rem .5rem; border:1px solid var(--line);
        border-radius:.3rem; min-width:16rem; margin:.2rem 0 .6rem; }
 pre.log { background:#0f1115; color:#d6d9de; padding:.8rem; font-size:.78rem;
        border-radius:.4rem; max-height:32rem; overflow:auto; }
 .muted { color:var(--muted); }
</style></head>
<body>
<header>
 <b>ray_tpu</b>
 <nav id="nav"></nav>
 <span class="muted" id="stamp" style="margin-left:auto"></span>
</header>
<main id="root">loading…</main>
<script>
const TABS = ["overview","nodes","actors","placement_groups","jobs","tasks",
              "events","logs","metrics"];
const j = async (u) => (await fetch(u)).json();
const esc = (s) => String(s).replaceAll("&","&amp;").replaceAll("<","&lt;")
  .replaceAll(">","&gt;").replaceAll('"',"&quot;").replaceAll("'","&#39;");
const fmt = (o) => o === null || o === undefined ? "" :
  esc(typeof o === "object" ? JSON.stringify(o) : String(o));
// Pill class names come from server data: only known state tokens may
// become CSS classes (everything is escaped before it hits innerHTML).
const pill = (s) => s ? `<span class="pill ${
  /^[A-Z_]+$/.test(s) ? s : ""}">${esc(s)}</span>` : "";
let filterText = "";

function table(rows, cols, opts) {
  opts = opts || {};
  if (!rows || !rows.length) return "<i class=muted>none</i>";
  cols = cols || Object.keys(rows[0]);
  const ft = filterText.toLowerCase();
  if (ft) rows = rows.filter(r => JSON.stringify(r).toLowerCase().includes(ft));
  let h = "<table><tr>" + cols.map(c => `<th>${c}</th>`).join("") + "</tr>";
  for (const r of rows) {
    h += "<tr>" + cols.map(c => {
      let v = r[c];
      if (opts.pills && opts.pills.includes(c)) return `<td>${pill(v)}</td>`;
      return `<td class=mono>${fmt(v)}</td>`;
    }).join("") + "</tr>";
  }
  return h + `</table><div class=muted>${rows.length} rows</div>`;
}
function searchBox() {
  return `<input id=filt type=search placeholder="filter…" ` +
         `value="${esc(filterText)}" oninput="onFilt(this)">`;
}
function onFilt(el) {
  filterText = el.value;
  render();
  const f = document.getElementById("filt");
  if (f) { f.focus(); f.setSelectionRange(f.value.length, f.value.length); }
}

let cache = {};
async function load(tab) {
  if (tab === "overview") {
    const [nodes, actors, ev] = await Promise.all([
      j("/api/nodes"), j("/api/actors"), j("/api/events?limit=15")]);
    return {nodes: nodes.nodes, actors: actors.actors, events: ev.events};
  }
  if (tab === "nodes") return j("/api/nodes");
  if (tab === "actors") return j("/api/actors");
  if (tab === "placement_groups") return j("/api/placement_groups");
  if (tab === "jobs") return j("/api/jobs");
  if (tab === "tasks") return j("/api/tasks/summary");
  if (tab === "events") return j("/api/events?limit=500");
  if (tab === "logs") return j("/api/logs");
  return {};
}
function overview(d) {
  const alive = d.nodes.filter(n => n.state === "ALIVE");
  const byState = {};
  for (const a of d.actors) byState[a.state] = (byState[a.state] || 0) + 1;
  const res = {};
  for (const n of alive) {
    for (const [k, v] of Object.entries(n.total || {})) {
      res[k] = res[k] || {total: 0, avail: 0};
      res[k].total += v; res[k].avail += (n.available || {})[k] ?? 0;
    }
  }
  let cards = `<div class=cards>
    <div class=card><div class=n>${alive.length}</div><div class=l>alive nodes</div></div>
    <div class=card><div class=n>${d.actors.length}</div><div class=l>actors</div></div>`;
  for (const [s, c] of Object.entries(byState))
    cards += `<div class=card><div class=n>${c}</div><div class=l>${pill(s)}</div></div>`;
  cards += "</div><h2>Resources</h2><div class=cards>";
  for (const [k, v] of Object.entries(res)) {
    const used = v.total - v.avail, pct = v.total ? 100 * used / v.total : 0;
    cards += `<div class=card style="min-width:14rem">
      <div class=l>${esc(k)}</div><div class=n>${(used/1e4).toFixed(1)} / ${(v.total/1e4).toFixed(1)}</div>
      <div class=bar><i style="width:${pct}%"></i></div></div>`;
  }
  cards += "</div><h2>Recent events</h2>" + table(
    (d.events || []).slice().reverse(),
    ["timestamp","severity","label","message"], {pills:["severity"]});
  return cards;
}
function render() {
  const tab = location.hash.replace("#", "") || "overview";
  document.getElementById("nav").innerHTML = TABS.map(t =>
    `<a href="#${t}" class="${t === tab ? 'active' : ''}">${t.replace("_"," ")}</a>`
  ).join("");
  const d = cache[tab];
  const root = document.getElementById("root");
  if (!d) { root.innerHTML = "loading…"; return; }
  if (tab === "overview") root.innerHTML = overview(d);
  else if (tab === "nodes") root.innerHTML = searchBox() + table(d.nodes, null, {pills:["state"]});
  else if (tab === "actors") root.innerHTML = searchBox() + table(d.actors,
    ["actor_id","class_name","name","state","node_id","worker_id","num_restarts"],
    {pills:["state"]});
  else if (tab === "placement_groups") root.innerHTML = searchBox() +
    table(d.pgs, null, {pills:["state"]});
  else if (tab === "jobs") root.innerHTML = searchBox() + table(d.jobs, null, {pills:["status"]});
  else if (tab === "tasks") root.innerHTML = "<h2>Task summary</h2><pre class=log>" +
    esc(JSON.stringify(d, null, 2)) + "</pre>";
  else if (tab === "events") root.innerHTML = searchBox() + table(
    (d.events || []).slice().reverse(),
    ["timestamp","severity","label","message","source_type"], {pills:["severity"]});
  else if (tab === "logs") {
    let h = "<h2>Session logs</h2>";
    for (const [node, reply] of Object.entries(d)) {
      const files = (reply && reply.files) || [];
      h += `<h2 class=mono>${esc(node)}</h2><ul>` + files.map(f =>
        `<li><a href="#" class="mono loglink" data-node="${esc(node)}" ` +
        `data-file="${esc(f)}">${esc(f)}</a></li>`
      ).join("") + "</ul>";
    }
    root.innerHTML = h + '<div id=logview></div>';
    for (const a of root.querySelectorAll("a.loglink"))
      a.addEventListener("click", (e) => {
        e.preventDefault();
        showLog(a.dataset.node, a.dataset.file);
      });
  }
  else if (tab === "metrics") root.innerHTML =
    '<p>Prometheus exposition at <a href="/metrics">/metrics</a>; file-SD + ' +
    'generated Grafana dashboard JSON live under the session dir ' +
    '(see util/metrics_export.py).</p>';
  document.getElementById("stamp").textContent =
    "updated " + new Date().toLocaleTimeString();
}
async function showLog(node, file) {
  const r = await j(`/api/logs?node_id=${encodeURIComponent(node)}` +
                    `&filename=${encodeURIComponent(file)}`);
  const reply = r[node] || {};
  const text = (reply.lines || []).join("\n");
  document.getElementById("logview").innerHTML =
    `<h2 class=mono>${esc(file)}</h2><pre class=log>${esc(text)}</pre>`;
}
let lastError = null;
async function refresh() {
  const tab = location.hash.replace("#", "") || "overview";
  try { cache[tab] = await load(tab); lastError = null; }
  catch (e) { lastError = e; }
  render();
  if (lastError) {
    document.getElementById("stamp").textContent =
      "backend unreachable: " + lastError;
  }
}
window.addEventListener("hashchange", refresh);
refresh(); setInterval(refresh, 5000);
</script></body></html>
"""


class Dashboard:
    def __init__(
        self,
        gcs_addr: Tuple[str, int],
        host: str = "127.0.0.1",
        port: int = 8265,
        session_name: str = "",
    ):
        self.gcs_addr = gcs_addr
        self.host = host
        self.port = port
        self.session_name = session_name
        self._conn = None
        self._runner = None
        self._sd_writer = None

    async def _gcs(self, method: str, payload: Optional[dict] = None):
        from ray_tpu._private import rpc

        if self._conn is None or self._conn.closed:
            self._conn = await rpc.connect(*self.gcs_addr)
        return await self._conn.call(method, payload or {})

    async def start(self) -> Tuple[str, int]:
        from aiohttp import web

        app = web.Application()
        app.router.add_get("/", self._index)
        app.router.add_get("/api/cluster_status", self._cluster_status)
        app.router.add_get("/api/nodes", self._nodes)
        app.router.add_get("/api/actors", self._actors)
        app.router.add_get("/api/jobs", self._jobs)
        app.router.add_get("/api/placement_groups", self._pgs)
        app.router.add_get("/api/tasks", self._tasks)
        app.router.add_get("/api/logs", self._logs)
        app.router.add_get("/api/tasks/summary", self._task_summary)
        app.router.add_get("/api/events", self._events)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/-/healthz", self._healthz)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = site._server.sockets[0].getsockname()[1]
        # Observability side outputs (reference: metrics_agent.py:595 file-SD
        # + dashboard/modules/metrics generated Grafana dashboards): a stock
        # Prometheus file_sd_config pointed at the session dir scrapes this
        # dashboard's /metrics; the Grafana JSON is provisioning-ready.
        try:
            import os
            import tempfile

            from ray_tpu.util.metrics_export import (
                PrometheusServiceDiscoveryWriter,
                write_grafana_dashboards,
            )

            session_dir = os.path.join(
                tempfile.gettempdir(),
                f"ray_tpu_{self.session_name or 'default'}",
            )
            self._sd_writer = PrometheusServiceDiscoveryWriter(
                lambda: [f"{self.host}:{self.port}"], session_dir
            )
            self._sd_writer.start()
            write_grafana_dashboards(session_dir)
        except Exception:
            pass
        return self.host, self.port

    async def stop(self) -> None:
        if self._sd_writer is not None:
            self._sd_writer.stop()
            self._sd_writer = None
        if self._runner is not None:
            await self._runner.cleanup()
        if self._conn is not None:
            await self._conn.close()

    # -- handlers ------------------------------------------------------------

    async def _index(self, request):
        from aiohttp import web

        return web.Response(text=INDEX_HTML, content_type="text/html")

    async def _healthz(self, request):
        from aiohttp import web

        return web.Response(text="success")

    async def _cluster_status(self, request):
        from aiohttp import web

        return web.json_response(await self._gcs("GetClusterStatus"))

    async def _nodes(self, request):
        from aiohttp import web

        return web.json_response(await self._gcs("GetAllNodes"))

    async def _actors(self, request):
        from aiohttp import web

        return web.json_response(await self._gcs("ListActors"))

    async def _jobs(self, request):
        from aiohttp import web
        from ray_tpu.job.job_manager import JOB_INFO_NS

        reply = await self._gcs("KVKeys", {"ns": JOB_INFO_NS, "prefix": ""})
        jobs = []
        for key in reply.get("keys", []):
            blob = (await self._gcs("KVGet", {"ns": JOB_INFO_NS, "key": key})).get(
                "value"
            )
            if blob:
                jobs.append(json.loads(blob))
        return web.json_response({"jobs": jobs})

    async def _pgs(self, request):
        from aiohttp import web

        return web.json_response(await self._gcs("ListPlacementGroups"))

    async def _events(self, request):
        """Structured cluster events (reference: dashboard event module over
        the event framework)."""
        from aiohttp import web

        q = request.query
        try:
            limit = min(int(q.get("limit", 500)), 10000)
        except ValueError:
            return web.json_response({"error": "limit must be int"}, status=400)
        return web.json_response(
            await self._gcs(
                "ListEvents",
                {
                    "severity": q.get("severity"),
                    "label": q.get("label"),
                    "limit": limit,
                },
            )
        )

    async def _tasks(self, request):
        from aiohttp import web

        reply = await self._gcs("ListTaskEvents", {"limit": 5000})
        return web.json_response(reply)

    async def _logs(self, request):
        """Log viewer endpoint: ?node_id=&filename=&worker_id=&tail= —
        proxies the raylet GetLog/ListLogs RPCs (reference: dashboard log
        module + state API get_log)."""
        from aiohttp import web

        from ray_tpu._private import rpc

        q = request.query
        nodes = (await self._gcs("GetAllNodes"))["nodes"]
        out = {}
        for n in nodes:
            if n["state"] != "ALIVE":
                continue
            if q.get("node_id") and n["node_id"] != q["node_id"]:
                continue
            try:
                conn = await rpc.connect(*n["addr"], retry=2)
            except rpc.RpcError:
                continue
            try:
                if q.get("filename") or q.get("worker_id"):
                    try:
                        tail = min(int(q.get("tail", 1000)), 100000)
                    except ValueError:
                        return web.json_response(
                            {"error": "tail must be an integer"}, status=400
                        )
                    reply = await conn.call(
                        "GetLog",
                        {
                            "filename": q.get("filename"),
                            "worker_id": q.get("worker_id"),
                            "stream": q.get("stream", "stderr"),
                            "tail": tail,
                        },
                    )
                else:
                    reply = await conn.call("ListLogs", {})
                out[n["node_id"]] = reply
            finally:
                await conn.close()
        return web.json_response(out)

    async def _metrics(self, request):
        """Prometheus text exposition: application metrics merged across all
        workers (the reference MetricsAgent role) followed by the runtime
        telemetry aggregate pulled from the GCS (GetTelemetry)."""
        import time as _time

        from aiohttp import web

        from ray_tpu._private import telemetry
        from ray_tpu._private.common import config
        from ray_tpu.util.metrics import METRICS_NS, render_prometheus

        keys = (await self._gcs("KVKeys", {"ns": METRICS_NS, "prefix": ""})).get(
            "keys", []
        )
        now = _time.time()
        stale_after = config.metrics_stale_after_s
        per_worker = {}
        for key in keys:
            blob = (await self._gcs("KVGet", {"ns": METRICS_NS, "key": key})).get(
                "value"
            )
            if not blob:
                continue
            snap = json.loads(blob)
            # Age out snapshots from workers that stopped flushing (dead
            # worker must not serve its last values forever). Unstamped
            # snapshots predate the _ts field and are kept.
            ts = snap.get("_ts")
            if ts is not None and now - ts > stale_after:
                await self._gcs("KVDel", {"ns": METRICS_NS, "key": key})
                continue
            per_worker[key] = snap
        text = render_prometheus(per_worker)
        try:
            reply = await self._gcs("GetTelemetry", {})
        except Exception:
            reply = None
        if reply:
            text += telemetry.render_runtime_prometheus(
                reply["telemetry"],
                worker_deadline_stats=reply.get("worker_deadline_stats"),
            )
        return web.Response(text=text, content_type="text/plain")

    async def _task_summary(self, request):
        from aiohttp import web

        reply = await self._gcs("ListTaskEvents", {"limit": 100000})
        latest: Dict[str, dict] = {}
        for e in reply["events"]:
            if e.get("state") in ("PROFILE", "SPAN"):
                continue  # phase/trace records, not lifecycle states
            cur = latest.get(e["task_id"])
            if cur is None or e["time"] >= cur["time"]:
                latest[e["task_id"]] = e
        summary: Dict[str, Dict[str, int]] = {}
        for e in latest.values():
            name = e.get("name") or "?"
            summary.setdefault(name, {})
            summary[name][e["state"]] = summary[name].get(e["state"], 0) + 1
        return web.json_response({"summary": summary, "total": len(latest)})


async def run_dashboard(gcs_addr, host="127.0.0.1", port=8265):
    dash = Dashboard(tuple(gcs_addr), host, port)
    bound = await dash.start()
    print(f"dashboard at http://{bound[0]}:{bound[1]}")
    while True:
        await asyncio.sleep(3600)
