"""Resident execution loop run inside each actor of a compiled DAG.

Analog of the reference's do_exec_tasks loop injected into actors by
compiled_dag_node.py: read input channels, run the bound method, write the
result to every consumer channel. A STOP sentinel propagates downstream and
terminates every loop.
"""

from __future__ import annotations

from typing import Any, Dict, List

from ray_tpu.dag.channel import Channel, make_channel

STOP = "__RT_DAG_STOP__"


def dag_exec_loop(actor_instance: Any, spec: Dict[str, Any]) -> int:
    """spec:
    method_name: str
    arg_specs: list of ("const", value) | ("chan", (name, size)) — positional
    kwarg_specs: {key: same}
    out_channels: [(name, size)]  (already created by the driver)
    Returns the number of executed iterations."""
    method = getattr(actor_instance, spec["method_name"])
    in_channels: List[Channel] = []
    arg_fns = []
    for kind, payload in spec["arg_specs"]:
        if kind == "const":
            arg_fns.append(("const", payload))
        else:
            ch = make_channel(payload)
            in_channels.append(ch)
            arg_fns.append(("chan", ch))
    kwarg_fns = {}
    for key, (kind, payload) in spec.get("kwarg_specs", {}).items():
        if kind == "const":
            kwarg_fns[key] = ("const", payload)
        else:
            ch = make_channel(payload)
            in_channels.append(ch)
            kwarg_fns[key] = ("chan", ch)
    outs = [make_channel(sp) for sp in spec["out_channels"]]

    iterations = 0

    def read_one(ch: Channel):
        """-> (value, stop, error). Upstream wire tuples are unwrapped here
        so user methods see raw values; upstream errors skip execution and
        propagate."""
        v = ch.read()
        if isinstance(v, str) and v == STOP:
            return None, True, None
        if isinstance(v, tuple) and len(v) == 2 and v[0] in ("ok", "err"):
            if v[0] == "err":
                return None, False, v[1]
            return v[1], False, None
        return v, False, None

    try:
        while True:
            stop = False
            upstream_err = None
            args = []
            for kind, payload in arg_fns:
                if kind == "const":
                    args.append(payload)
                else:
                    v, s, e = read_one(payload)
                    stop = stop or s
                    upstream_err = upstream_err or e
                    args.append(v)
            kwargs = {}
            for key, (kind, payload) in kwarg_fns.items():
                if kind == "const":
                    kwargs[key] = payload
                else:
                    v, s, e = read_one(payload)
                    stop = stop or s
                    upstream_err = upstream_err or e
                    kwargs[key] = v
            if stop:
                for out in outs:
                    out.write(STOP)
                return iterations
            if upstream_err is not None:
                wire = ("err", upstream_err)
            else:
                try:
                    result = method(*args, **kwargs)
                    wire = ("ok", result)
                except Exception as e:  # propagate downstream instead of dying
                    wire = ("err", e)
            for out in outs:
                out.write(wire)
            iterations += 1
    finally:
        for ch in in_channels:
            ch.close()
        for out in outs:
            out.close()


def unwrap(wire: Any) -> Any:
    """Driver/consumer side: re-raise executor errors."""
    if isinstance(wire, tuple) and len(wire) == 2 and wire[0] in ("ok", "err"):
        if wire[0] == "err":
            raise wire[1]
        return wire[1]
    return wire
