"""DAG node types: lazy graph construction via .bind().

Analog of python/ray/dag/{dag_node.py,class_node.py,input_node.py,
output_node.py}: `actor.method.bind(upstream)` builds a ClassMethodNode;
`with InputNode() as inp:` marks the graph entry; MultiOutputNode fans
several leaves out to the caller.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    # Transport for this node's OUTPUT edges: None (pickle shm channel),
    # "tensor" (array-native shm channel), or "device" (compiled ppermute
    # device channel; reference analog: TorchTensorType/with_tensor_transport
    # with transport="nccl" on aDAG edges).
    _tensor_transport: Optional[str] = None
    _transport_meta: Optional[Dict[str, Any]] = None

    def experimental_compile(self, *, max_buf_size: int = 10 * 1024 * 1024):
        from ray_tpu.dag.compiled import CompiledDAG

        return CompiledDAG(self, max_buf_size=max_buf_size)

    def with_tensor_transport(
        self,
        transport: str = "tensor",
        *,
        group_name: str = "default",
        src: int = 0,
        dst: int = 1,
    ) -> "DAGNode":
        """Mark this node's outputs as array payloads.

        transport="tensor": raw-buffer shm channels (dtype/shape header +
        memcpy — no pickle). transport="device": compiled device channels —
        shm control frame + jitted ppermute payload hop between collective
        ranks `src` (producer) and `dst` (consumer) of xla group
        `group_name`; see docs/collectives.md. Only actor→actor edges ride
        the device path — driver-facing edges degrade to "tensor".
        Reference: DAGNode.with_tensor_transport(...)."""
        self._tensor_transport = transport
        if transport == "device":
            self._transport_meta = {
                "group": group_name, "src": int(src), "dst": int(dst)
            }
        return self

    def _upstream(self) -> List["DAGNode"]:
        return []


class InputNode(DAGNode):
    """Graph entry placeholder (reference: input_node.py)."""

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        return None


class ClassMethodNode(DAGNode):
    def __init__(self, actor_handle, method_name: str, args: Tuple, kwargs: Dict):
        self.actor = actor_handle
        self.method_name = method_name
        self.args = args
        self.kwargs = kwargs
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, InputNode):
                continue
        ups = [a for a in list(args) + list(kwargs.values()) if isinstance(a, DAGNode)]
        self._ups = ups

    def _upstream(self) -> List[DAGNode]:
        return self._ups


class MultiOutputNode(DAGNode):
    def __init__(self, outputs: List[DAGNode]):
        self.outputs = list(outputs)

    def _upstream(self) -> List[DAGNode]:
        return self.outputs


def bind(actor_method, *args, **kwargs) -> ClassMethodNode:
    """actor.method.bind(...) — attached to ActorMethod by ray_tpu.actor."""
    return ClassMethodNode(
        actor_method._handle, actor_method._name, args, kwargs
    )
