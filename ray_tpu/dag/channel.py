"""Mutable shared-memory channels (single-writer, single-reader, one slot).

Analog of python/ray/experimental/channel/shared_memory_channel.py backed by
the C++ mutable-object machinery (experimental_mutable_object_manager.h:37):
a fixed shm segment reused for every message — no per-message allocation,
naming, or RPC. Synchronization is a seqlock: the writer bumps the sequence
to odd while writing and even when done; the reader spins (briefly) then
sleeps, and validates the sequence didn't move mid-read.

Layout: [seq: u64][length: u64][payload...]
"""

from __future__ import annotations

import pickle
import struct
import time
from typing import Any, Optional, Tuple

import cloudpickle

from ray_tpu._private import shm

HEADER = struct.Struct("<QQ")
DATA_OFFSET = 64  # keep payload cache-line aligned


class ChannelFullError(Exception):
    pass


class Channel:
    """One-slot mutable channel over a named shm segment."""

    def __init__(self, name: str, max_buf_size: int = 10 * 1024 * 1024, *,
                 create: bool = False):
        self.name = name
        self.max_buf_size = max_buf_size
        if create:
            self._seg = shm.create(name, DATA_OFFSET + max_buf_size)
            HEADER.pack_into(self._seg.view, 0, 0, 0)
        else:
            self._seg = shm.open_rw(name)
        self._last_read_seq = 0

    # -- writer side ---------------------------------------------------------

    def write(self, value: Any) -> None:
        payload = cloudpickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
        if len(payload) > self.max_buf_size:
            raise ChannelFullError(
                f"message of {len(payload)} bytes exceeds channel capacity "
                f"{self.max_buf_size}; recompile with a larger max_buf_size"
            )
        view = self._seg.view
        seq, _ = HEADER.unpack_from(view, 0)
        HEADER.pack_into(view, 0, seq + 1, len(payload))  # odd = writing
        view[DATA_OFFSET : DATA_OFFSET + len(payload)] = payload
        HEADER.pack_into(view, 0, seq + 2, len(payload))  # even = sealed

    # -- reader side ---------------------------------------------------------

    def read(self, timeout: Optional[float] = None) -> Any:
        """Block until a message newer than the last read arrives."""
        view = self._seg.view
        deadline = None if timeout is None else time.monotonic() + timeout
        polls = 0
        while True:
            seq, length = HEADER.unpack_from(view, 0)
            if seq % 2 == 0 and seq > self._last_read_seq:
                payload = bytes(view[DATA_OFFSET : DATA_OFFSET + length])
                seq2, _ = HEADER.unpack_from(view, 0)
                if seq2 == seq:  # seqlock validate: no concurrent rewrite
                    # Decode strictly AFTER validation, from the private
                    # copy: torn slot bytes must be retried, never parsed.
                    self._last_read_seq = seq
                    return self._decode_payload(payload)
            polls += 1
            if deadline is not None and polls % 64 == 0 and time.monotonic() > deadline:
                raise TimeoutError(f"channel {self.name} read timed out")
            # sched_yield-style polling: busy-spinning starves the peer on
            # CPU-constrained hosts (measured 100x worse on 1 core), while
            # sleep(0) keeps hot ping-pong ~100us. Back off when idle.
            if polls < 2000:
                time.sleep(0)
            elif polls < 20000:
                time.sleep(0.00005)
            else:
                time.sleep(0.001)

    def _decode_payload(self, payload: bytes) -> Any:
        """Subclass hook: turn a validated snapshot of the slot into a value
        (TensorChannel parses a raw array header instead of unpickling)."""
        return cloudpickle.loads(payload)

    def close(self, unlink: bool = False) -> None:
        try:
            self._seg.close()
        except Exception:
            pass
        if unlink:
            try:
                shm.unlink(self.name)
            except Exception:
                pass


def make_channel(spec, *, create: bool = False) -> Channel:
    """Open a channel from its wire spec (name, size[, kind[, meta]]): kind
    "tensor" -> array-native TensorChannel, "device" -> compiled
    device-to-device DeviceTensorChannel (meta holds the collective group +
    src/dst ranks), else the pickle Channel."""
    name, size = spec[0], spec[1]
    kind = spec[2] if len(spec) > 2 else "chan"
    if kind == "tensor":
        from ray_tpu.dag.tensor_channel import TensorChannel

        return TensorChannel(name, size, create=create)
    if kind == "device":
        from ray_tpu.dag.tensor_channel import DeviceTensorChannel

        meta = spec[3] if len(spec) > 3 else None
        return DeviceTensorChannel(name, size, create=create, meta=meta)
    return Channel(name, size, create=create)
