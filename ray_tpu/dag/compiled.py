"""CompiledDAG: turn a bind() graph into resident actor loops + channels.

Analog of python/ray/dag/compiled_dag_node.py (CompiledDAG:288): compilation
walks the graph, allocates one shm channel per edge, and starts a resident
execution loop on every participating actor. execute() then costs one
channel write + one channel read — no task submission, scheduling, or
object-store round trip per call (the reference's aDAG motivation).
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.common import RayTpuError
from ray_tpu.dag.channel import Channel, make_channel
from ray_tpu.dag.exec_loop import STOP, unwrap
from ray_tpu.dag.nodes import ClassMethodNode, DAGNode, InputNode, MultiOutputNode


class CompiledDAGRef:
    """Future for one execute() call (reference: CompiledDAGRef)."""

    def __init__(self, dag: "CompiledDAG", idx: int):
        self._dag = dag
        self._idx = idx
        self._value: Any = None
        self._consumed = False

    def get(self, timeout: Optional[float] = 30.0) -> Any:
        if not self._consumed:
            wires = [ch.read(timeout) for ch in self._dag._output_channels]
            # Mark consumed before unwrap: an executor error must not wedge
            # the DAG (the slot IS consumed — the error is the result).
            self._consumed = True
            self._dag._pending_ref = None
            try:
                self._value = (
                    unwrap(wires[0])
                    if not self._dag._multi_output
                    else [unwrap(w) for w in wires]
                )
            except Exception as e:
                self._value = e
                raise
        if isinstance(self._value, BaseException):
            raise self._value
        return self._value


class CompiledDAG:
    def __init__(self, leaf: DAGNode, *, max_buf_size: int = 10 * 1024 * 1024):
        self._max_buf = max_buf_size
        self._uid = uuid.uuid4().hex[:10]
        self._counter = 0
        self._pending_ref: Optional[CompiledDAGRef] = None
        self._torn_down = False

        self._multi_output = isinstance(leaf, MultiOutputNode)
        leaves = leaf.outputs if self._multi_output else [leaf]
        for lf in leaves:
            if not isinstance(lf, ClassMethodNode):
                raise RayTpuError("compiled DAG leaves must be actor method nodes")

        # Topological order over ClassMethodNodes.
        order: List[ClassMethodNode] = []
        seen: Dict[int, bool] = {}

        def visit(node: DAGNode):
            if id(node) in seen:
                return
            seen[id(node)] = True
            for up in node._upstream():
                visit(up)
            if isinstance(node, ClassMethodNode):
                order.append(node)

        for lf in leaves:
            visit(lf)
        if not order:
            raise RayTpuError("empty compiled DAG")
        actors = {n.actor._actor_id for n in order}
        if len(actors) != len(order):
            raise RayTpuError(
                "compiled DAG supports one node per actor (each actor hosts "
                "one resident loop)"
            )
        self._nodes = order

        # One channel per edge. driver->node edges for InputNode args,
        # node->node edges for DAGNode args, leaf->driver edges for outputs.
        self._input_channels: List[Channel] = []  # driver writes
        self._output_channels: List[Channel] = []  # driver reads
        node_out_specs: Dict[int, List[Tuple[str, int]]] = {id(n): [] for n in order}
        node_specs: Dict[int, Dict[str, Any]] = {}

        self._all_chan_names: List[str] = []

        def new_chan_spec(kind: str = "chan", meta=None):
            self._counter += 1
            name = f"rtdag_{self._uid}_{self._counter}"
            self._all_chan_names.append(name)
            if meta is not None:
                return (name, self._max_buf, kind, meta)
            return (name, self._max_buf, kind)

        for node in order:
            arg_specs = []
            for a in node.args:
                arg_specs.append(self._arg_spec(a, node_out_specs, new_chan_spec))
            kwarg_specs = {
                k: self._arg_spec(v, node_out_specs, new_chan_spec)
                for k, v in node.kwargs.items()
            }
            node_specs[id(node)] = {
                "method_name": node.method_name,
                "arg_specs": arg_specs,
                "kwarg_specs": kwarg_specs,
            }
        for lf in leaves:
            spec = new_chan_spec(
                "tensor" if lf._tensor_transport else "chan"
            )
            self._output_channels.append(make_channel(spec, create=True))
            node_out_specs[id(lf)].append(spec)

        # Start the resident loops (one long-running actor task per node).
        self._loop_refs = []
        for node in order:
            spec = node_specs[id(node)]
            spec["out_channels"] = node_out_specs[id(node)]
            from ray_tpu.actor import ActorMethod

            loop = ActorMethod(_handle_of(node), "__rt_dag_loop__")
            self._loop_refs.append(loop.remote(spec))

    def _arg_spec(self, a, node_out_specs, new_chan_spec):
        if isinstance(a, InputNode):
            spec = new_chan_spec("tensor" if a._tensor_transport else "chan")
            ch = make_channel(spec, create=True)
            self._input_channels.append(ch)
            return ("chan", spec)
        if isinstance(a, ClassMethodNode):
            # Edge transport follows the PRODUCER's annotation
            # (reference: with_tensor_transport on the upstream node).
            # actor->actor edges are the only ones eligible for the compiled
            # device path; everything else degrades to the shm tensor wire.
            if a._tensor_transport == "device":
                spec = new_chan_spec("device", a._transport_meta)
            else:
                spec = new_chan_spec("tensor" if a._tensor_transport else "chan")
            # Create driver-side so the consumer can open it immediately.
            make_channel(spec, create=True).close()
            node_out_specs[id(a)].append(spec)
            return ("chan", spec)
        if isinstance(a, DAGNode):
            raise RayTpuError(f"unsupported DAG node arg {type(a).__name__}")
        return ("const", a)

    # -- execution -----------------------------------------------------------

    def execute(self, *args) -> CompiledDAGRef:
        if self._torn_down:
            raise RayTpuError("compiled DAG was torn down")
        if self._pending_ref is not None:
            raise RayTpuError(
                "previous execute() result not consumed yet (one in-flight "
                "execution per compiled DAG; call .get() first)"
            )
        value = args[0] if len(args) == 1 else tuple(args)
        for ch in self._input_channels:
            ch.write(value)
        ref = CompiledDAGRef(self, 0)
        self._pending_ref = ref
        return ref

    def teardown(self) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        try:
            for ch in self._input_channels:
                ch.write(STOP)
            # Loops ack by forwarding STOP to the output channels.
            for ch in self._output_channels:
                try:
                    ch.read(timeout=10)
                except Exception:
                    pass
        finally:
            for ch in self._input_channels + self._output_channels:
                ch.close()
            from ray_tpu._private import shm

            for name in self._all_chan_names:
                try:
                    shm.unlink(name)
                except Exception:
                    pass

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass


def _handle_of(node: ClassMethodNode):
    return node.actor
