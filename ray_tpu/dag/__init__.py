"""ray_tpu.dag: compiled actor graphs over shared-memory channels.

Analog of the reference's compiled graphs / aDAG (python/ray/dag +
python/ray/experimental/channel): a lazy DAG of actor-method calls is
compiled once; per-call RPC + object-store traffic is replaced by
preallocated mutable shm channels (seqlock'd single-writer ring of one
slot), with each actor running a resident execution loop. On TPU pods the
inter-host tensor path composes with jit collective programs (ICI) — the
channel tier here is the intra-host control/data plane, like the reference's
mutable plasma objects (experimental_mutable_object_manager.h:37).

    import ray_tpu
    from ray_tpu import dag

    a = Adder.remote(); b = Doubler.remote()
    with dag.InputNode() as inp:
        graph = b.double.bind(a.add.bind(inp))
    compiled = graph.experimental_compile()
    assert compiled.execute(3).get() == 8   # (3+1)*2
"""

from ray_tpu.dag.compiled import CompiledDAG, CompiledDAGRef
from ray_tpu.dag.nodes import ClassMethodNode, DAGNode, InputNode, MultiOutputNode

__all__ = [
    "ClassMethodNode",
    "CompiledDAG",
    "CompiledDAGRef",
    "DAGNode",
    "InputNode",
    "MultiOutputNode",
]
