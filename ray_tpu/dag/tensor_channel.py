"""Array-native channels for compiled DAGs + the ICI device-to-device path.

Analog of python/ray/experimental/channel/torch_tensor_nccl_channel.py: the
reference moves GPU tensors between compiled-DAG actors over NCCL, skipping
the object store and host memory. The TPU-native translation has two layers:

1. ``TensorChannel`` — a shm channel specialized for jax/numpy arrays: raw
   dtype/shape header + buffer memcpy instead of cloudpickle (which both
   copies and byte-stuffs). Cross-actor, same-host.

2. ``make_ici_transfer`` — the true device-to-device path: a jitted
   shard_map ppermute hop over a live Mesh. On TPU hardware the transfer
   rides ICI links without touching host memory; the same program compiles
   and runs on a virtual CPU mesh for testing. Both DAG actors participate
   in the one SPMD program (multi-controller jax), exactly as both ranks
   participate in the reference's NCCL send/recv.
"""

from __future__ import annotations

import struct
from functools import partial
from typing import Any

import numpy as np

from ray_tpu.dag.channel import DATA_OFFSET, HEADER, Channel, ChannelFullError

_MAGIC_ARRAY = 0xA1
_MAGIC_ARRAY_OK = 0xA2  # array wrapped in the exec-loop ("ok", value) tuple
_MAGIC_PICKLE = 0xB2
# [magic: u8][ndim: u8][dtype-len: u8][reserved: u8][nbytes: u64]
_AHDR = struct.Struct("<BBBxQ")


class TensorChannel(Channel):
    """One-slot shm channel whose array payloads skip pickle entirely.

    Synchronization is inherited from Channel (seqlock read loop + decode
    hook); only the payload encoding differs.
    """

    # -- writer side ---------------------------------------------------------

    def write(self, value: Any) -> None:
        magic = _MAGIC_ARRAY
        if (
            type(value) is tuple
            and len(value) == 2
            and isinstance(value[0], str)
            and value[0] == "ok"
        ):
            # Exec-loop wire tuple: keep the array fast path for the value.
            magic = _MAGIC_ARRAY_OK
            value = value[1]
        arr = self._as_array(value)
        if arr is None or arr.dtype.hasobject:
            payload = _pickle_payload(
                ("ok", value) if magic == _MAGIC_ARRAY_OK else value
            )
            self._write_raw(_MAGIC_PICKLE, payload, b"", ())
            return
        shape = arr.shape  # BEFORE ascontiguousarray (it promotes 0-d to 1-d)
        arr = np.ascontiguousarray(arr)
        self._write_raw(
            magic, arr.view(np.uint8).reshape(-1), arr.dtype.str.encode(), shape
        )

    @staticmethod
    def _as_array(value: Any):
        if isinstance(value, np.ndarray):
            return value
        t = type(value)
        if t.__module__.startswith("jax") or t.__name__ == "ArrayImpl":
            import jax

            return np.asarray(jax.device_get(value))
        return None

    def _write_raw(self, magic: int, body, dtype_b: bytes, shape) -> None:
        shape_b = b"".join(struct.pack("<q", d) for d in shape)
        nbytes = body.nbytes if isinstance(body, np.ndarray) else len(body)
        total = _AHDR.size + len(dtype_b) + len(shape_b) + nbytes
        if total > self.max_buf_size:
            raise ChannelFullError(
                f"message of {total} bytes exceeds channel capacity "
                f"{self.max_buf_size}; recompile with a larger max_buf_size"
            )
        view = self._seg.view
        seq, _ = HEADER.unpack_from(view, 0)
        HEADER.pack_into(view, 0, seq + 1, total)  # odd = writing
        off = DATA_OFFSET
        _AHDR.pack_into(view, off, magic, len(shape), len(dtype_b), nbytes)
        off += _AHDR.size
        view[off : off + len(dtype_b)] = dtype_b
        off += len(dtype_b)
        view[off : off + len(shape_b)] = shape_b
        off += len(shape_b)
        if isinstance(body, np.ndarray):
            np.frombuffer(view, dtype=np.uint8, count=nbytes, offset=off)[:] = body
        else:
            view[off : off + nbytes] = body
        HEADER.pack_into(view, 0, seq + 2, total)  # even = sealed

    # -- reader side ---------------------------------------------------------

    def _decode_payload(self, payload: bytes) -> Any:
        """Parse a validated snapshot (Channel.read's seqlock already copied
        it out of the slot, so no extra array copy is needed here)."""
        magic, ndim, dlen, nbytes = _AHDR.unpack_from(payload, 0)
        off = _AHDR.size
        dtype_b = payload[off : off + dlen]
        off += dlen
        shape = tuple(
            struct.unpack_from("<q", payload, off + 8 * i)[0] for i in range(ndim)
        )
        off += 8 * ndim
        if magic == _MAGIC_PICKLE:
            import cloudpickle

            return cloudpickle.loads(payload[off : off + nbytes])
        data = np.frombuffer(payload, dtype=np.uint8, count=nbytes, offset=off)
        out = data.view(np.dtype(dtype_b.decode())).reshape(shape)
        return ("ok", out) if magic == _MAGIC_ARRAY_OK else out


def _pickle_payload(value) -> bytes:
    import pickle

    import cloudpickle

    return cloudpickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)


def make_ici_transfer(mesh, axis: str, src: int, dst: int):
    """Compile a device-to-device shard transfer over a live mesh.

    Returns a jitted fn moving the ``src`` device's shard of ``x`` onto the
    ``dst`` device's shard slot via one ppermute hop — on TPU this is one
    ICI link traversal with no host round trip (reference analog: NCCL
    send/recv between aDAG actors). Other shards pass through unchanged.
    """
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    @partial(shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis))
    def _hop(x):
        moved = jax.lax.ppermute(x, axis, perm=[(src, dst)])
        idx = jax.lax.axis_index(axis)
        # dst's slot takes the moved shard; everyone else keeps their own.
        return jax.numpy.where(idx == dst, moved, x)

    return jax.jit(_hop)
