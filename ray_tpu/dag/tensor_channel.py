"""Array-native channels for compiled DAGs + the ICI device-to-device path.

Analog of python/ray/experimental/channel/torch_tensor_nccl_channel.py: the
reference moves GPU tensors between compiled-DAG actors over NCCL, skipping
the object store and host memory. The TPU-native translation has two layers:

1. ``TensorChannel`` — a shm channel specialized for jax/numpy arrays: raw
   dtype/shape header + buffer memcpy instead of cloudpickle (which both
   copies and byte-stuffs). Cross-actor, same-host.

2. ``DeviceTensorChannel`` — the compiled-graph device channel: the shm slot
   carries only a CONTROL FRAME (magic + dtype + shape), and the payload hops
   device-to-device through a cached compiled ``ppermute`` program
   (ray_tpu.util.collective.mesh_ops.MeshCollectives over a 2-device
   submesh). On TPU hardware the transfer rides ICI links without touching
   host memory; both DAG actors join the one SPMD program, exactly as both
   ranks participate in the reference's NCCL send/recv. Wire format and mode
   selection: docs/collectives.md.

3. ``make_ici_transfer`` — the minimal building block underneath (2): a
   jitted shard_map ppermute hop over a live Mesh, kept as the unit-testable
   primitive.
"""

from __future__ import annotations

import struct
from functools import partial
from typing import Any

import numpy as np

from ray_tpu.dag.channel import DATA_OFFSET, HEADER, Channel, ChannelFullError

_MAGIC_ARRAY = 0xA1
_MAGIC_ARRAY_OK = 0xA2  # array wrapped in the exec-loop ("ok", value) tuple
_MAGIC_PICKLE = 0xB2
_MAGIC_DEVICE = 0xD1  # control frame: payload hopped device->device
_MAGIC_DEVICE_OK = 0xD2  # device frame wrapped in ("ok", value)
# [magic: u8][ndim: u8][dtype-len: u8][reserved: u8][nbytes: u64]
_AHDR = struct.Struct("<BBBxQ")

# Loopback handoff: when one process addresses both endpoint devices (CPU
# sim, or a DAG pinned to one TPU host) the hopped dst shard is parked here
# by channel name for the same-process reader — the device array never
# leaves the device. Cross-process readers fall back to the frame body.
_DEVICE_SLOTS: dict = {}


class TensorChannel(Channel):
    """One-slot shm channel whose array payloads skip pickle entirely.

    Synchronization is inherited from Channel (seqlock read loop + decode
    hook); only the payload encoding differs.
    """

    # -- writer side ---------------------------------------------------------

    def write(self, value: Any) -> None:
        magic = _MAGIC_ARRAY
        if (
            type(value) is tuple
            and len(value) == 2
            and isinstance(value[0], str)
            and value[0] == "ok"
        ):
            # Exec-loop wire tuple: keep the array fast path for the value.
            magic = _MAGIC_ARRAY_OK
            value = value[1]
        arr = self._as_array(value)
        if arr is None or arr.dtype.hasobject:
            payload = _pickle_payload(
                ("ok", value) if magic == _MAGIC_ARRAY_OK else value
            )
            self._write_raw(_MAGIC_PICKLE, payload, b"", ())
            return
        shape = arr.shape  # BEFORE ascontiguousarray (it promotes 0-d to 1-d)
        arr = np.ascontiguousarray(arr)
        self._write_raw(
            magic, arr.view(np.uint8).reshape(-1), arr.dtype.str.encode(), shape
        )

    @staticmethod
    def _as_array(value: Any):
        if isinstance(value, np.ndarray):
            return value
        t = type(value)
        if t.__module__.startswith("jax") or t.__name__ == "ArrayImpl":
            import jax

            return np.asarray(jax.device_get(value))
        return None

    def _write_raw(self, magic: int, body, dtype_b: bytes, shape) -> None:
        shape_b = b"".join(struct.pack("<q", d) for d in shape)
        nbytes = body.nbytes if isinstance(body, np.ndarray) else len(body)
        total = _AHDR.size + len(dtype_b) + len(shape_b) + nbytes
        if total > self.max_buf_size:
            raise ChannelFullError(
                f"message of {total} bytes exceeds channel capacity "
                f"{self.max_buf_size}; recompile with a larger max_buf_size"
            )
        view = self._seg.view
        seq, _ = HEADER.unpack_from(view, 0)
        HEADER.pack_into(view, 0, seq + 1, total)  # odd = writing
        off = DATA_OFFSET
        _AHDR.pack_into(view, off, magic, len(shape), len(dtype_b), nbytes)
        off += _AHDR.size
        view[off : off + len(dtype_b)] = dtype_b
        off += len(dtype_b)
        view[off : off + len(shape_b)] = shape_b
        off += len(shape_b)
        if isinstance(body, np.ndarray):
            np.frombuffer(view, dtype=np.uint8, count=nbytes, offset=off)[:] = body
        else:
            view[off : off + nbytes] = body
        HEADER.pack_into(view, 0, seq + 2, total)  # even = sealed

    # -- reader side ---------------------------------------------------------

    def _decode_payload(self, payload: bytes) -> Any:
        """Parse a validated snapshot (Channel.read's seqlock already copied
        it out of the slot, so no extra array copy is needed here)."""
        magic, ndim, dlen, nbytes = _AHDR.unpack_from(payload, 0)
        off = _AHDR.size
        dtype_b = payload[off : off + dlen]
        off += dlen
        shape = tuple(
            struct.unpack_from("<q", payload, off + 8 * i)[0] for i in range(ndim)
        )
        off += 8 * ndim
        if magic == _MAGIC_PICKLE:
            import cloudpickle

            return cloudpickle.loads(payload[off : off + nbytes])
        data = np.frombuffer(payload, dtype=np.uint8, count=nbytes, offset=off)
        out = data.view(np.dtype(dtype_b.decode())).reshape(shape)
        return ("ok", out) if magic == _MAGIC_ARRAY_OK else out


class DeviceTensorChannel(TensorChannel):
    """Compiled-graph device channel: shm control frame + ppermute payload.

    ``meta`` names the producer/consumer collective ranks:
    ``{"group": <collective group>, "src": <rank>, "dst": <rank>}``.
    The first array write resolves one of three modes (docs/collectives.md):

    - ``ici``: multi-controller jax (the named xla collective group spans
      processes). The slot carries only [magic, dtype, shape]; the payload
      moves through a cached compiled ppermute over the 2-device
      (src, dst) submesh of the group's ici mesh — writer stages its shard,
      reader joins the same SPMD program with a zeros contribution and keeps
      the hopped dst shard. No host memory, no object store.
    - ``loopback``: one process addresses both devices (CPU sim / one-host
      DAG). The hop still runs — the dst-device array is parked in
      ``_DEVICE_SLOTS`` for a same-process reader — and the frame also
      carries the raw bytes so a cross-process reader on the same host
      degrades to the TensorChannel path instead of deadlocking.
    - ``shm``: no usable device pair; plain TensorChannel behavior.

    Non-array values (STOP sentinel, errors, pickled results) always take
    the inherited shm path, so DAG teardown and error propagation are
    identical across modes.
    """

    def __init__(self, name: str, max_buf_size: int = 10 * 1024 * 1024, *,
                 create: bool = False, meta=None):
        super().__init__(name, max_buf_size, create=create)
        meta = meta or {}
        self.group_name = meta.get("group", "default")
        self.src = int(meta.get("src", 0))
        self.dst = int(meta.get("dst", 1))
        self._mode = None
        self._engine = None

    # -- mode + engine resolution --------------------------------------------

    def _resolve(self):
        if self._mode is not None:
            return self._mode
        try:
            import jax

            from ray_tpu.util.collective import collective as _col
            from ray_tpu.util.collective.mesh_ops import MeshCollectives
            from jax.sharding import Mesh

            group = None
            if _col.is_group_initialized(self.group_name):
                group = _col._manager.get(self.group_name)
            if (
                group is not None
                and group.engine is not None
                and group.world_size > max(self.src, self.dst)
                and jax.process_count() > 1
            ):
                ici = group.engine.mesh
                devs = np.asarray(
                    [ici.devices.flat[self.src], ici.devices.flat[self.dst]]
                )
                self._engine = MeshCollectives(
                    Mesh(devs, ("chan",)), axis="chan",
                    group_name=f"chan:{self.group_name}",
                )
                self._mode = "ici"
            elif (
                jax.process_count() == 1
                and self.src != self.dst
                and len(jax.devices()) > max(self.src, self.dst)
            ):
                devs = np.asarray(
                    [jax.devices()[self.src], jax.devices()[self.dst]]
                )
                self._engine = MeshCollectives(
                    Mesh(devs, ("chan",)), axis="chan",
                    group_name=f"chan:{self.group_name}",
                )
                self._mode = "loopback"
            else:
                self._mode = "shm"
        except Exception:
            self._mode = "shm"
        return self._mode

    # -- writer side ---------------------------------------------------------

    def write(self, value: Any) -> None:
        magic = _MAGIC_DEVICE
        if (
            type(value) is tuple
            and len(value) == 2
            and isinstance(value[0], str)
            and value[0] == "ok"
        ):
            magic = _MAGIC_DEVICE_OK
            value = value[1]
        arr = self._device_array(value)
        if arr is None or self._resolve() == "shm":
            # shm mode / non-array payloads: inherited TensorChannel wire.
            restored = (
                ("ok", value) if magic == _MAGIC_DEVICE_OK else value
            )
            super().write(restored)
            return
        shape = tuple(arr.shape)
        dtype_b = np.dtype(arr.dtype).str.encode()
        hopped = self._engine.permute(
            self._engine.stage_local(arr, 0, cache=False), [(0, 1)]
        )
        if self._mode == "ici":
            # Control frame only; the payload lives on the dst device. The
            # frame seals AFTER the hop completes so a reader that sees it
            # can immediately consume the shard.
            self._write_raw(magic, b"", dtype_b, shape)
            return
        # loopback: park the dst-device shard for a same-process reader and
        # ALSO carry the bytes so a cross-process reader still decodes.
        for s in hopped.addressable_shards:
            start = s.index[0].start or 0
            if start == 1:
                _DEVICE_SLOTS[self.name] = s.data.reshape(shape)
                break
        host = np.ascontiguousarray(np.asarray(value))
        self._write_raw(
            magic, host.view(np.uint8).reshape(-1), dtype_b, shape
        )

    @staticmethod
    def _device_array(value):
        """Arrays eligible for the device hop (numpy is staged; jax.Array
        single-device payloads pass through)."""
        if isinstance(value, np.ndarray) and not value.dtype.hasobject:
            return value
        t = type(value)
        if t.__module__.startswith("jax") or t.__name__ == "ArrayImpl":
            return value
        return None

    # -- reader side ---------------------------------------------------------

    def _decode_payload(self, payload: bytes) -> Any:
        magic, ndim, dlen, nbytes = _AHDR.unpack_from(payload, 0)
        if magic not in (_MAGIC_DEVICE, _MAGIC_DEVICE_OK):
            return super()._decode_payload(payload)
        off = _AHDR.size
        dtype = np.dtype(payload[off : off + dlen].decode())
        off += dlen
        shape = tuple(
            struct.unpack_from("<q", payload, off + 8 * i)[0]
            for i in range(ndim)
        )
        off += 8 * ndim
        mode = self._resolve()
        if mode == "loopback" or self._mode == "loopback":
            slot = _DEVICE_SLOTS.pop(self.name, None)
            if slot is not None:
                out = slot
            else:
                # Cross-process reader on the same host: frame body carries
                # the bytes (TensorChannel degradation).
                data = np.frombuffer(
                    payload, dtype=np.uint8, count=nbytes, offset=off
                )
                out = data.view(dtype).reshape(shape)
        elif mode == "ici":
            # Join the writer's SPMD hop with a zeros contribution; keep the
            # shard that landed on our (dst) device.
            zeros = np.zeros(shape, dtype)
            hopped = self._engine.permute(
                self._engine.stage_local(zeros, 1, cache=False), [(0, 1)]
            )
            out = None
            for s in hopped.addressable_shards:
                if (s.index[0].start or 0) == 1:
                    out = s.data.reshape(shape)
                    break
            if out is None:
                raise RuntimeError(
                    f"device channel {self.name}: dst shard not addressable"
                )
        else:
            raise RuntimeError(
                f"device channel {self.name}: control frame received but no "
                f"device path is available in this process (group "
                f"{self.group_name!r} not initialized?)"
            )
        return ("ok", out) if magic == _MAGIC_DEVICE_OK else out


def _pickle_payload(value) -> bytes:
    import pickle

    import cloudpickle

    return cloudpickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)


def make_ici_transfer(mesh, axis: str, src: int, dst: int):
    """Compile a device-to-device shard transfer over a live mesh.

    Returns a jitted fn moving the ``src`` device's shard of ``x`` onto the
    ``dst`` device's shard slot via one ppermute hop — on TPU this is one
    ICI link traversal with no host round trip (reference analog: NCCL
    send/recv between aDAG actors). Other shards pass through unchanged.
    """
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    @partial(shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis))
    def _hop(x):
        moved = jax.lax.ppermute(x, axis, perm=[(src, dst)])
        idx = jax.lax.axis_index(axis)
        # dst's slot takes the moved shard; everyone else keeps their own.
        return jax.numpy.where(idx == dst, moved, x)

    return jax.jit(_hop)
