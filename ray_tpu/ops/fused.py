"""Small fused ops: RMSNorm and large-vocab cross entropy.

XLA already fuses most elementwise chains into neighboring matmuls; these
exist for the two spots where explicit control wins: (a) RMSNorm in f32 on
bf16 activations without an f32 round-trip through HBM, (b) cross entropy
that never materializes [B*T, V] probabilities in f32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def fused_rmsnorm(x, weight, *, eps: float = 1e-6):
    """RMSNorm with f32 statistics on any-dtype input; output in input dtype."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)).astype(x.dtype)


def softmax_cross_entropy(logits, labels, *, ignore_index: int = -100):
    """Token-level CE on [..., V] logits and integer labels.

    Computed as logsumexp - label_logit in f32 without forming probabilities;
    positions equal to ignore_index contribute 0 and are excluded from the
    mean. Returns (mean_loss, valid_token_count).
    """
    lf = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(lf, axis=-1)
    label_safe = jnp.where(labels == ignore_index, 0, labels)
    picked = jnp.take_along_axis(
        lf, label_safe[..., None], axis=-1
    ).squeeze(-1)
    per_tok = lse - picked
    mask = (labels != ignore_index).astype(jnp.float32)
    n = jnp.maximum(mask.sum(), 1.0)
    return (per_tok * mask).sum() / n, n
