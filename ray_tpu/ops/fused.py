"""Small fused ops: RMSNorm and large-vocab cross entropy.

XLA already fuses most elementwise chains into neighboring matmuls; these
exist for the two spots where explicit control wins: (a) RMSNorm in f32 on
bf16 activations without an f32 round-trip through HBM, (b) cross entropy
that never materializes [B*T, V] probabilities in f32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def fused_rmsnorm(x, weight, *, eps: float = 1e-6):
    """RMSNorm with f32 statistics on any-dtype input; output in input dtype."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)).astype(x.dtype)


def softmax_cross_entropy(logits, labels, *, ignore_index: int = -100):
    """Token-level CE on [..., V] logits and integer labels.

    Computed as logsumexp - label_logit in f32 without forming probabilities;
    positions equal to ignore_index contribute 0 and are excluded from the
    mean. Returns (mean_loss, valid_token_count).
    """
    lf = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(lf, axis=-1)
    label_safe = jnp.where(labels == ignore_index, 0, labels)
    picked = jnp.take_along_axis(
        lf, label_safe[..., None], axis=-1
    ).squeeze(-1)
    per_tok = lse - picked
    mask = (labels != ignore_index).astype(jnp.float32)
    n = jnp.maximum(mask.sum(), 1.0)
    return (per_tok * mask).sum() / n, n


def lm_head_cross_entropy(
    hidden,
    unembed,
    targets,
    *,
    chunk_tokens: int = 2048,
    ignore_index: int = -100,
):
    """Fused LM-head + token CE that never materializes [B*T, V] logits.

    `hidden` [B, T, d] (compute dtype) is scanned in token chunks; each chunk
    computes its logits (one [chunk, d] @ [d, V] matmul), reduces to
    logsumexp - label_logit in f32, and is rematerialized in the backward
    pass. Peak logits memory drops from B*T*V*4 bytes (gigabytes at GPT-2
    vocab) to chunk_tokens*V*4, which is what lets large-vocab models train
    at large batch on one chip. Returns (mean_loss, valid_token_count).
    """
    B, T, d = hidden.shape
    n = B * T
    h = hidden.reshape(n, d)
    t = targets.reshape(n)
    pad = (-n) % chunk_tokens
    if pad:
        h = jnp.concatenate([h, jnp.zeros((pad, d), h.dtype)], axis=0)
        t = jnp.concatenate(
            [t, jnp.full((pad,), ignore_index, t.dtype)], axis=0
        )
    chunks = h.shape[0] // chunk_tokens
    h = h.reshape(chunks, chunk_tokens, d)
    t = t.reshape(chunks, chunk_tokens)

    @jax.checkpoint
    def chunk_loss(hc, tc):
        logits = (hc @ unembed.astype(hc.dtype)).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        safe = jnp.where(tc == ignore_index, 0, tc)
        picked = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
        mask = (tc != ignore_index).astype(jnp.float32)
        return ((lse - picked) * mask).sum(), mask.sum()

    def body(carry, xs):
        loss_sum, count = carry
        ls, ns = chunk_loss(*xs)
        return (loss_sum + ls, count + ns), None

    (loss_sum, count), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)), (h, t)
    )
    count = jnp.maximum(count, 1.0)
    return loss_sum / count, count
