"""Blocked (flash) attention as a Pallas TPU kernel.

Online-softmax attention tiled for the MXU: the grid walks (batch*heads,
q-block, k-block) with the k dimension innermost; running max/denominator and
the output accumulator live in VMEM scratch that persists across the k steps
and is flushed on the last one. f32 accumulation, bf16-friendly inputs.

Dispatch: `mha` picks this kernel on TPU, falls back to an XLA einsum
implementation elsewhere (tests run the kernel in interpret mode on tiny
shapes via `flash_attention(..., interpret=True)`).

Backward pass uses recompute (custom_vjp re-derives the tile softmax),
trading FLOPs for the O(T^2) memory XLA would otherwise materialize.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

_BIG_NEG = -1e30


def _compiler_params(pltpu, **kw):
    """pltpu.TPUCompilerParams was renamed CompilerParams across jax minor
    releases; build whichever this jax ships."""
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)


def _attn_fwd_kernel(
    q_ref, k_ref, v_ref,  # inputs
    o_ref,  # output
    acc_ref, m_ref, l_ref,  # VMEM scratch, persistent over the k grid dim
    *, block_q: int, block_k: int, num_k: int, scale: float, causal: bool,
    seq_q: int, seq_k: int,
):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _BIG_NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _body():
        q = q_ref[0].astype(jnp.float32)  # [bq, D]
        k = k_ref[0].astype(jnp.float32)  # [bk, D]
        v = v_ref[0].astype(jnp.float32)  # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_pos < seq_k  # padding keys past the true length
        if causal:
            mask &= q_pos >= k_pos
        s = jnp.where(mask, s, _BIG_NEG)

        m_prev = m_ref[...]  # [bq, 128] (lane-replicated)
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        alpha = jnp.exp(m_prev - m_new)  # [bq, 128]
        p = jnp.exp(s - m_new[:, :1])  # [bq, bk]
        p = jnp.where(mask, p, 0.0)
        l_ref[...] = l_prev * alpha + jnp.broadcast_to(
            p.sum(axis=-1, keepdims=True), l_prev.shape
        )
        m_ref[...] = m_new
        if seq_k % block_k:
            # Padded K/V rows may be NaN-filled; p is 0 there but 0*NaN=NaN.
            krow = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, 1), 0
            )
            v = jnp.where(krow < seq_k, v, 0.0)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, D]
        acc_ref[...] = acc_ref[...] * alpha[:, :1] + pv

    if causal:
        # Blocks strictly above the diagonal contribute nothing; skip them.
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            _body()
    else:
        _body()

    @pl.when(ki == num_k - 1)
    def _flush():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _attn_fwd_kernel_lse(
    q_ref, k_ref, v_ref,
    o_ref, lse_ref,
    acc_ref, m_ref, l_ref,
    *, block_q: int, block_k: int, num_k: int, scale: float, causal: bool,
    seq_q: int, seq_k: int,
):
    """Forward that additionally writes LSE = m + log(l) per q row — the
    residual the tiled backward needs to re-derive tile softmax without
    another online-max pass."""
    from jax.experimental import pallas as pl

    _attn_fwd_kernel(
        q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
        block_q=block_q, block_k=block_k, num_k=num_k, scale=scale,
        causal=causal, seq_q=seq_q, seq_k=seq_k,
    )
    ki = pl.program_id(2)

    @pl.when(ki == num_k - 1)
    def _flush_lse():
        # Per-q-row scalars must live on sublanes; the block's minor dim
        # must be 128-divisible OR equal the array dim, so an 8-wide
        # replicated minor axis is the cheapest legal layout (16x less HBM
        # than jax's own 128-wide l/m residuals).
        lse = m_ref[...] + jnp.log(jnp.maximum(l_ref[...], 1e-30))
        lse_ref[0] = lse[:, :8]


def _flash_fwd(q, k, v, *, causal, scale, block_q, block_k, interpret,
               with_lse: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, T, D = q.shape
    S = k.shape[1]
    block_q = min(block_q, T)
    block_k = min(block_k, S)
    num_q = pl.cdiv(T, block_q)
    num_k = pl.cdiv(S, block_k)

    kernel = functools.partial(
        _attn_fwd_kernel_lse if with_lse else _attn_fwd_kernel,
        block_q=block_q,
        block_k=block_k,
        num_k=num_k,
        scale=scale,
        causal=causal,
        seq_q=T,
        seq_k=S,
    )
    out_shape = jax.ShapeDtypeStruct((BH, T, D), q.dtype)
    out_specs = pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0))
    if with_lse:
        out_shape = [
            out_shape,
            jax.ShapeDtypeStruct((BH, T, 8), jnp.float32),
        ]
        out_specs = [
            out_specs,
            pl.BlockSpec((1, block_q, 8), lambda bh, qi, ki: (bh, qi, 0)),
        ]
    return pl.pallas_call(
        kernel,
        grid=(BH, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=_compiler_params(
            pltpu, dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_fwd(
        q, k, v, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    o, lse = _flash_fwd(
        q, k, v, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret, with_lse=True,
    )
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, res, do):
    """Tiled FlashAttention-2 backward: two pallas kernels (dq; dk/dv), each
    re-deriving its softmax tile from (q, k, lse) — nothing O(T·S) ever
    touches HBM (the previous recompute path materialized full f32 score
    matrices through XLA, which both OOMed large batches and made the step
    bandwidth-bound)."""
    q, k, v, o, lse = res
    BH, T, _ = q.shape
    delta = (do.astype(jnp.float32) * o.astype(jnp.float32)).sum(-1)
    # Same sublane-aligned [BH, T, 8] layout as lse.
    delta = jnp.broadcast_to(delta[..., None], (BH, T, 8))
    dq = _flash_bwd_dq(
        q, k, v, do, lse, delta, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    dk, dv = _flash_bwd_dkv(
        q, k, v, do, lse, delta, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _bwd_tile(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qi, ki, *,
              block_q, block_k, scale, causal, seq_q, seq_k):
    """Shared per-tile computation of both backward kernels: load + sanitize
    padded rows + re-derive the softmax tile. Returns (q, k, v, do, p, ds).

    Sanitizing at load matters: pallas pads partial blocks with arbitrary
    (possibly NaN) data, and a NaN anywhere in a dot input poisons the whole
    contraction even where the weight is 0."""
    q = q_ref[0].astype(jnp.float32)  # [bq, D]
    k = k_ref[0].astype(jnp.float32)  # [bk, D]
    v = v_ref[0].astype(jnp.float32)  # [bk, D]
    do = do_ref[0].astype(jnp.float32)  # [bq, D]
    lse = lse_ref[0][:, :1]  # [bq, 1] (lane-replicated input)
    delta = delta_ref[0][:, :1]  # [bq, 1]
    if seq_q % block_q:
        qrow = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0
        )
        qvalid = qrow < seq_q
        q = jnp.where(qvalid, q, 0.0)
        do = jnp.where(qvalid, do, 0.0)
        lse = jnp.where(qvalid, lse, 0.0)
        delta = jnp.where(qvalid, delta, 0.0)
    if seq_k % block_k:
        krow = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0
        )
        kvalid = krow < seq_k
        k = jnp.where(kvalid, k, 0.0)
        v = jnp.where(kvalid, v, 0.0)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [bq, bk]
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    mask = (k_pos < seq_k) & (q_pos < seq_q)
    if causal:
        mask &= q_pos >= k_pos
    p = jnp.where(mask, jnp.exp(s - lse), 0.0)  # [bq, bk]
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bq, bk]
    # Explicit where: p=0 times a NaN dp entry would still poison the dot.
    ds = jnp.where(mask, p * (dp - delta) * scale, 0.0)  # [bq, bk]
    return q, k, v, do, p, ds


def _attn_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref,
    acc_ref,
    *, block_q: int, block_k: int, num_k: int, scale: float, causal: bool,
    seq_q: int, seq_k: int,
):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _body():
        _, k, _, _, _, ds = _bwd_tile(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qi, ki,
            block_q=block_q, block_k=block_k, scale=scale, causal=causal,
            seq_q=seq_q, seq_k=seq_k,
        )
        acc_ref[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, D]

    if causal:
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            _body()
    else:
        _body()

    @pl.when(ki == num_k - 1)
    def _flush():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _attn_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref,
    dk_acc_ref, dv_acc_ref,
    *, block_q: int, block_k: int, num_q: int, scale: float, causal: bool,
    seq_q: int, seq_k: int,
):
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    def _body():
        q, _, _, do, p, ds = _bwd_tile(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qi, ki,
            block_q=block_q, block_k=block_k, scale=scale, causal=causal,
            seq_q=seq_q, seq_k=seq_k,
        )
        dv_acc_ref[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bk, D]
        dk_acc_ref[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bk, D]

    if causal:
        # Only q blocks at/below the diagonal see this k block.
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            _body()
    else:
        _body()

    @pl.when(qi == num_q - 1)
    def _flush():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _flash_bwd_dq(q, k, v, do, lse, delta, *, causal, scale,
                  block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, T, D = q.shape
    S = k.shape[1]
    block_q = min(block_q, T)
    block_k = min(block_k, S)
    num_q = pl.cdiv(T, block_q)
    num_k = pl.cdiv(S, block_k)
    kernel = functools.partial(
        _attn_bwd_dq_kernel,
        block_q=block_q, block_k=block_k, num_k=num_k, scale=scale,
        causal=causal, seq_q=T, seq_k=S,
    )
    return pl.pallas_call(
        kernel,
        grid=(BH, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 8), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 8), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=_compiler_params(
            pltpu, dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, do, lse, delta)


def _flash_bwd_dkv(q, k, v, do, lse, delta, *, causal, scale,
                   block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, T, D = q.shape
    S = k.shape[1]
    block_q = min(block_q, T)
    block_k = min(block_k, S)
    num_q = pl.cdiv(T, block_q)
    num_k = pl.cdiv(S, block_k)
    kernel = functools.partial(
        _attn_bwd_dkv_kernel,
        block_q=block_q, block_k=block_k, num_q=num_q, scale=scale,
        causal=causal, seq_q=T, seq_k=S,
    )
    return pl.pallas_call(
        kernel,
        grid=(BH, num_k, num_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 8), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 8), lambda bh, ki, qi: (bh, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32),
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=_compiler_params(
            pltpu, dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, do, lse, delta)


def _xla_attention_bhtd(q, k, v, *, causal, scale):
    """Reference path on [BH, T, D] used for backward + non-TPU fallback."""
    s = jnp.einsum(
        "btd,bsd->bts", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        T, S = q.shape[1], k.shape[1]
        mask = jnp.arange(T)[:, None] >= jnp.arange(S)[None, :]
        s = jnp.where(mask[None], s, _BIG_NEG)
    m = s.max(axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = p / p.sum(axis=-1, keepdims=True)
    return jnp.einsum("bts,bsd->btd", p, v.astype(jnp.float32)).astype(q.dtype)


def flash_attention(
    q, k, v,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
):
    """Flash attention on [B, T, H, D] inputs (grouped-query: H_kv may divide H)."""
    B, T, H, D = q.shape
    Hk = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    if Hk != H:
        rep = H // Hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    # [B, T, H, D] -> [B*H, T, D]
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, k.shape[1], D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, v.shape[1], D)
    of = _flash(qf, kf, vf, causal, scale, block_q, block_k, interpret)
    return of.reshape(B, H, T, D).transpose(0, 2, 1, 3)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def mha(q, k, v, *, causal: bool = False, scale: Optional[float] = None,
        impl: str = "auto"):
    """Multi-head attention dispatch on [B, T, H, D].

    impl: 'auto' (pallas on TPU, XLA elsewhere) | 'pallas' | 'xla'.
    """
    if impl == "auto":
        impl = "pallas" if _on_tpu() else "xla"
    if impl == "pallas":
        return flash_attention(q, k, v, causal=causal, scale=scale)
    B, T, H, D = q.shape
    Hk = k.shape[2]
    if Hk != H:
        rep = H // Hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, k.shape[1], D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, v.shape[1], D)
    of = _xla_attention_bhtd(qf, kf, vf, causal=causal, scale=scale)
    return of.reshape(B, H, T, D).transpose(0, 2, 1, 3)
