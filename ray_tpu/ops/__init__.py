"""ray_tpu.ops: TPU kernels for the hot ops.

The compute path of the framework is JAX/XLA; these Pallas kernels cover the
ops where hand-tiling beats XLA's default lowering (attention above all —
the reference delegates this tier to NCCL-adjacent GPU libraries; here it is
MXU-tiled Pallas). Every op has an XLA fallback so the same code runs on CPU
(tests) and TPU (bench) unchanged.
"""

from ray_tpu.ops.flash_attention import flash_attention, mha
from ray_tpu.ops.fused import fused_rmsnorm, softmax_cross_entropy

__all__ = [
    "flash_attention",
    "mha",
    "fused_rmsnorm",
    "softmax_cross_entropy",
]
