"""The autoscaling control loop.

Analog of autoscaler/v2/autoscaler.py + _private/autoscaler.py
(StandardAutoscaler) + resource_demand_scheduler.py: demand = pending
worker leases reported by raylets; supply = alive nodes' resources. Scale
up when demand goes unmet past the upscale delay (bin-packing demand onto
the cheapest satisfying node type), scale down nodes idle past the idle
timeout, clamped to per-type min/max workers.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)


@dataclass
class AutoscalerConfig:
    upscale_delay_s: float = 1.0
    idle_timeout_s: float = 30.0
    poll_interval_s: float = 1.0
    max_launches_per_round: int = 4


@dataclass
class _NodeTracker:
    """One scaling unit: a single host or a whole TPU pod slice gang."""

    provider_node_ids: List[str]
    node_type: str
    launched_at: float = field(default_factory=time.monotonic)
    idle_since: Optional[float] = None


class Autoscaler:
    """Drive with repeated update() calls (or run() in a thread). Reads
    cluster state through the connected driver's state APIs."""

    def __init__(
        self,
        provider,
        config: Optional[AutoscalerConfig] = None,
        state_fn=None,
    ):
        self.provider = provider
        self.config = config or AutoscalerConfig()
        # state_fn() -> per-node stats list (GetNodeStats shape). Default
        # reads through the connected driver; the simulated-cluster harness
        # injects its own collector since a SimCluster has no driver.
        self._state_fn = state_fn
        self._tracked: Dict[str, _NodeTracker] = {}
        self._demand_since: Optional[float] = None

    # -- state collection ----------------------------------------------------

    def _cluster_state(self) -> Tuple[int, List[dict]]:
        """-> (total pending leases, per-node stats)."""
        if self._state_fn is not None:
            stats = self._state_fn()
        else:
            from ray_tpu.util.state.api import _each_raylet

            stats = _each_raylet({})
        pending = sum(s.get("pending_leases", 0) for s in stats)
        return pending, stats

    @staticmethod
    def _pending_demands(stats: List[dict]) -> List[Dict[str, int]]:
        demands: List[Dict[str, int]] = []
        for s in stats:
            demands.extend(s.get("pending_demand") or [])
        return demands

    # -- scaling decisions ---------------------------------------------------

    def _reconcile_provider(self) -> int:
        """Advance the provider's node state machine and repair tracked
        gangs with FAILED members (a TPU slice is only usable whole, so a
        lost host is re-created in place — reference: GCP provider node
        status handling + slice-gang repair). Returns replacements made."""
        poll = getattr(self.provider, "poll", None)
        if poll is None:
            return 0
        poll()
        failed = set(getattr(self.provider, "failed_nodes", lambda: [])())
        if not failed:
            return 0
        repaired = 0
        forget = getattr(self.provider, "forget_node", lambda _p: None)
        for t in self._tracked.values():
            for i, pid in enumerate(list(t.provider_node_ids)):
                if pid not in failed:
                    continue
                # Create the replacement FIRST: if it fails, the pid stays
                # FAILED and tracked so the next round retries the repair.
                try:
                    new_pid = self.provider.create_node(t.node_type)
                except Exception:
                    logger.exception(
                        "gang repair: re-create of %s (%s) failed; will retry",
                        pid, t.node_type,
                    )
                    continue
                # A FAILED node may still EXIST in GCE (STOPPED/PREEMPTED) —
                # delete it so it doesn't bill as an untracked orphan. On
                # success the provider's TERMINATING -> poll path drops the
                # record once GCE confirms; on failure forget it from the
                # provider (it is out of the gang now) with a loud warning.
                if self.provider.terminate_node(pid) is False:
                    forget(pid)
                    logger.error(
                        "gang repair: could not delete failed node %s — it "
                        "may still exist (and bill) in GCE; clean up "
                        "manually", pid
                    )
                t.provider_node_ids[i] = new_pid
                repaired += 1
                logger.warning(
                    "gang repair: replaced failed node %s with %s", pid, new_pid
                )
        return repaired

    def update(self) -> Dict[str, int]:
        """One reconcile round; returns {"launched": n, "terminated": m}."""
        self._reconcile_provider()
        pending, stats = self._cluster_state()
        now = time.monotonic()
        launched = terminated = 0

        # Ensure per-type minimums.
        counts: Dict[str, int] = {}
        for t in self._tracked.values():
            counts[t.node_type] = counts.get(t.node_type, 0) + 1
        for node_type, spec in self.provider.node_types.items():
            while counts.get(node_type, 0) < spec.get("min_workers", 0):
                launched += self._launch(node_type)
                counts[node_type] = counts.get(node_type, 0) + 1

        # Upscale on sustained unmet demand.
        if pending > 0:
            if self._demand_since is None:
                self._demand_since = now
            elif now - self._demand_since >= self.config.upscale_delay_s:
                demands = self._pending_demands(stats)
                for i in range(
                    min(self.config.max_launches_per_round, pending)
                ):
                    node_type = self._pick_type(
                        demands[i] if i < len(demands) else None
                    )
                    if node_type is None:
                        # This shape fits no type (or no headroom) — a later
                        # demand may still be satisfiable.
                        continue
                    launched += self._launch(node_type)
                self._demand_since = None
        else:
            self._demand_since = None

        # Downscale idle tracked nodes (slice gangs go together).
        busy_ids = {
            s["node_id"]
            for s in stats
            if s.get("num_workers", 0) - s.get("num_idle", 0) > 0
            or s.get("pending_leases", 0) > 0
        }
        for key, t in list(self._tracked.items()):
            raylet_of = getattr(self.provider, "raylet_node_id", lambda _p: None)
            is_busy = any(
                (raylet_of(pid) in busy_ids) if raylet_of(pid) else False
                for pid in t.provider_node_ids
            )
            if is_busy:
                t.idle_since = None
                continue
            if t.idle_since is None:
                t.idle_since = now
                continue
            spec = self.provider.node_types.get(t.node_type, {})
            if (
                now - t.idle_since >= self.config.idle_timeout_s
                and self._count(t.node_type) > spec.get("min_workers", 0)
            ):
                # A TPU pod slice is one failure/billing domain: its hosts
                # terminate together (reference: TPU pod scale-down removes
                # whole replicas, never individual slice hosts).
                remaining = []
                for pid in t.provider_node_ids:
                    if self.provider.terminate_node(pid) is False:
                        remaining.append(pid)
                    else:
                        terminated += 1
                if not remaining:
                    del self._tracked[key]
                else:
                    # Keep only the failed pids so the retry round neither
                    # re-terminates nor re-counts nodes already TERMINATING.
                    t.provider_node_ids = remaining
                    logger.warning(
                        "downscale of %s incomplete; will retry", t.node_type
                    )
        return {"launched": launched, "terminated": terminated}

    def _count(self, node_type: str) -> int:
        return sum(1 for t in self._tracked.values() if t.node_type == node_type)

    def _pick_type(self, demand: Optional[Dict[str, int]] = None) -> Optional[str]:
        """Cheapest node type with headroom that covers the demand shape
        (reference: resource_demand_scheduler bin-packing). With no shape,
        smallest type with headroom; with a shape that provably fits no
        type, None — launching hardware that can never satisfy the demand
        would just churn."""
        from ray_tpu._private.common import RESOURCE_UNIT

        candidates = sorted(
            self.provider.node_types.items(),
            key=lambda kv: sum(kv[1].get("resources", {}).values()),
        )
        fallback = None
        for node_type, spec in candidates:
            if self._count(node_type) >= spec.get("max_workers", 0):
                continue
            if fallback is None:
                fallback = node_type
            if demand and self._covers(spec, demand, RESOURCE_UNIT):
                return node_type
        return None if demand else fallback

    @staticmethod
    def _covers(spec: dict, demand: Dict[str, int], unit: int) -> bool:
        """True when ONE host of this type could grant a lease with this
        demand shape. Every lease is granted by a single raylet — a gang
        workload expresses slice-wide placement through the TPU-{pod}-head
        resource plus *per-host* chip counts on each member lease — so
        per-host resources are never scaled by slice size. Scaling (the old
        behavior) judged e.g. TPU:8 coverable by 4-chip hosts and churned
        slice launches that could never grant the lease."""
        have = spec.get("resources", {})
        for r, units in demand.items():
            if r.startswith("node:"):
                continue
            if r.startswith("TPU-") and r.endswith("-head"):
                # Gang resource TPU-{pod}-head: only a slice of that exact
                # pod type will ever advertise it.
                pod = r[len("TPU-") : -len("-head")]
                if spec.get("tpu_pod_slice") == pod or f"TPU-{pod}-head" in have:
                    continue
                return False
            if have.get(r, 0.0) * unit < units:
                return False
        return True

    def _launch(self, node_type: str) -> int:
        """Launch one *unit* of the type: a single host, or every host of a
        TPU pod slice as a gang (reference: TPU pod worker groups scale in
        whole slices). Returns hosts launched. Partially-created gangs are
        still tracked so the downscaler reclaims them."""
        spec = self.provider.node_types.get(node_type, {})
        n = int(spec.get("workers_per_slice", 1))
        if n == 1 and spec.get("tpu_pod_slice"):
            from ray_tpu._private.accelerators import TPUAcceleratorManager

            n = TPUAcceleratorManager.get_num_workers_in_pod(
                spec["tpu_pod_slice"]
            )
        pids: List[str] = []
        try:
            for _ in range(max(1, n)):
                pids.append(self.provider.create_node(node_type))
        except Exception:
            logger.exception(
                "slice launch of %s failed after %d/%d hosts; tracking the "
                "partial gang for reclamation",
                node_type,
                len(pids),
                n,
            )
        if pids:
            self._tracked[pids[0]] = _NodeTracker(pids, node_type)
        return len(pids)

    # -- loop ----------------------------------------------------------------

    def run(self, stop_event=None) -> None:
        while stop_event is None or not stop_event.is_set():
            try:
                self.update()
            except Exception:
                logger.exception("autoscaler round failed")
            time.sleep(self.config.poll_interval_s)
