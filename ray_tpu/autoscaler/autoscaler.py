"""The autoscaling control loop.

Analog of autoscaler/v2/autoscaler.py + _private/autoscaler.py
(StandardAutoscaler) + resource_demand_scheduler.py: demand = pending
worker leases reported by raylets; supply = alive nodes' resources. Scale
up when demand goes unmet past the upscale delay (bin-packing demand onto
the cheapest satisfying node type), scale down nodes idle past the idle
timeout, clamped to per-type min/max workers.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)


@dataclass
class AutoscalerConfig:
    upscale_delay_s: float = 1.0
    idle_timeout_s: float = 30.0
    poll_interval_s: float = 1.0
    max_launches_per_round: int = 4


@dataclass
class _NodeTracker:
    provider_node_id: str
    node_type: str
    launched_at: float = field(default_factory=time.monotonic)
    idle_since: Optional[float] = None


class Autoscaler:
    """Drive with repeated update() calls (or run() in a thread). Reads
    cluster state through the connected driver's state APIs."""

    def __init__(self, provider, config: Optional[AutoscalerConfig] = None):
        self.provider = provider
        self.config = config or AutoscalerConfig()
        self._tracked: Dict[str, _NodeTracker] = {}
        self._demand_since: Optional[float] = None

    # -- state collection ----------------------------------------------------

    def _cluster_state(self) -> Tuple[int, List[dict]]:
        """-> (total pending leases, per-node stats)."""
        from ray_tpu._private import worker as worker_mod
        from ray_tpu.util.state.api import _each_raylet

        stats = _each_raylet({})
        pending = sum(s.get("pending_leases", 0) for s in stats)
        return pending, stats

    # -- scaling decisions ---------------------------------------------------

    def update(self) -> Dict[str, int]:
        """One reconcile round; returns {"launched": n, "terminated": m}."""
        pending, stats = self._cluster_state()
        now = time.monotonic()
        launched = terminated = 0

        # Ensure per-type minimums.
        counts: Dict[str, int] = {}
        for t in self._tracked.values():
            counts[t.node_type] = counts.get(t.node_type, 0) + 1
        for node_type, spec in self.provider.node_types.items():
            while counts.get(node_type, 0) < spec.get("min_workers", 0):
                self._launch(node_type)
                counts[node_type] = counts.get(node_type, 0) + 1
                launched += 1

        # Upscale on sustained unmet demand.
        if pending > 0:
            if self._demand_since is None:
                self._demand_since = now
            elif now - self._demand_since >= self.config.upscale_delay_s:
                for _ in range(
                    min(self.config.max_launches_per_round, pending)
                ):
                    node_type = self._pick_type()
                    if node_type is None:
                        break
                    self._launch(node_type)
                    launched += 1
                self._demand_since = None
        else:
            self._demand_since = None

        # Downscale idle tracked nodes.
        busy_ids = {
            s["node_id"]
            for s in stats
            if s.get("num_workers", 0) - s.get("num_idle", 0) > 0
            or s.get("pending_leases", 0) > 0
        }
        for pid, t in list(self._tracked.items()):
            raylet_id = getattr(self.provider, "raylet_node_id", lambda _p: None)(pid)
            is_busy = raylet_id in busy_ids if raylet_id else False
            if is_busy:
                t.idle_since = None
                continue
            if t.idle_since is None:
                t.idle_since = now
                continue
            spec = self.provider.node_types.get(t.node_type, {})
            if (
                now - t.idle_since >= self.config.idle_timeout_s
                and self._count(t.node_type) > spec.get("min_workers", 0)
            ):
                self.provider.terminate_node(pid)
                del self._tracked[pid]
                terminated += 1
        return {"launched": launched, "terminated": terminated}

    def _count(self, node_type: str) -> int:
        return sum(1 for t in self._tracked.values() if t.node_type == node_type)

    def _pick_type(self) -> Optional[str]:
        """Smallest type with headroom (reference bin-packs demand shapes;
        single-resource-type clusters reduce to this)."""
        best = None
        for node_type, spec in sorted(
            self.provider.node_types.items(),
            key=lambda kv: sum(kv[1].get("resources", {}).values()),
        ):
            if self._count(node_type) < spec.get("max_workers", 0):
                best = node_type
                break
        return best

    def _launch(self, node_type: str) -> None:
        pid = self.provider.create_node(node_type)
        self._tracked[pid] = _NodeTracker(pid, node_type)

    # -- loop ----------------------------------------------------------------

    def run(self, stop_event=None) -> None:
        while stop_event is None or not stop_event.is_set():
            try:
                self.update()
            except Exception:
                logger.exception("autoscaler round failed")
            time.sleep(self.config.poll_interval_s)
