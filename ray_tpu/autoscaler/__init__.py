"""ray_tpu.autoscaler: demand-driven cluster scaling.

Analog of python/ray/autoscaler (v2 architecture: autoscaler/v2/
autoscaler.py + scheduler.py + instance_manager, consuming
GcsAutoscalerStateManager state): the Autoscaler polls cluster state —
pending worker-lease demand and per-node utilization — and asks a
NodeProvider to launch or terminate nodes. Providers: FakeNodeProvider
(in-process raylets via cluster_utils, the reference's fake_multi_node
test provider) and GCETPUNodeProvider (TPU-VM command construction).
"""

from ray_tpu.autoscaler.autoscaler import Autoscaler, AutoscalerConfig
from ray_tpu.autoscaler.node_provider import (
    FakeNodeProvider,
    GCETPUNodeProvider,
    NodeProvider,
)

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "FakeNodeProvider",
    "GCETPUNodeProvider",
    "NodeProvider",
]
