"""Node providers: pluggable node lifecycle backends.

Analog of python/ray/autoscaler/node_provider.py and the cloud
implementations under python/ray/autoscaler/_private/: a provider knows how
to create/terminate/list nodes of configured node types.
"""

from __future__ import annotations

import logging
import uuid
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


class NodeProvider:
    """Interface (reference: node_provider.py NodeProvider)."""

    def __init__(self, node_types: Optional[Dict[str, dict]] = None):
        # node_types: name -> {"resources": {...}, "min_workers", "max_workers"}
        self.node_types = node_types or {
            "worker": {"resources": {"CPU": 2.0}, "min_workers": 0, "max_workers": 4}
        }

    def create_node(self, node_type: str) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Adds/removes in-process raylets on the running cluster — the
    reference's fake_multi_node provider (autoscaler tests run against it in
    CI rather than a cloud)."""

    def __init__(self, cluster, node_types: Optional[Dict[str, dict]] = None):
        super().__init__(node_types)
        self.cluster = cluster  # ray_tpu.cluster_utils.Cluster
        self._nodes: Dict[str, Any] = {}

    def create_node(self, node_type: str) -> str:
        spec = self.node_types[node_type]
        res = dict(spec["resources"])
        node = self.cluster.add_node(
            num_cpus=res.pop("CPU", 1.0),
            num_tpus=res.pop("TPU", 0.0),
            resources=res,
        )
        pid = f"fake-{node_type}-{uuid.uuid4().hex[:6]}"
        self._nodes[pid] = node
        logger.info("fake provider launched %s (%s)", pid, spec["resources"])
        return pid

    def terminate_node(self, provider_node_id: str) -> None:
        node = self._nodes.pop(provider_node_id, None)
        if node is not None:
            self.cluster.remove_node(node)
            logger.info("fake provider terminated %s", provider_node_id)

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)

    def raylet_node_id(self, provider_node_id: str) -> Optional[str]:
        node = self._nodes.get(provider_node_id)
        return getattr(node, "node_id", None) if node is not None else None


# GCE TPU-VM node lifecycle states (reference: the GCP API's node states,
# gcp/node_provider.py _get_node status handling).
REQUESTED = "REQUESTED"  # create issued, no describe yet
PROVISIONING = "PROVISIONING"  # GCE reports CREATING
READY = "READY"
TERMINATING = "TERMINATING"  # delete issued, awaiting disappearance
FAILED = "FAILED"  # create exhausted retries / node vanished


def _error_text(e: Exception) -> str:
    """Lower-cased message of a runner failure, including the gcloud output
    that CalledProcessError keeps in .output/.stderr rather than str(e)."""
    parts = [str(e)]
    for attr in ("output", "stderr"):
        v = getattr(e, attr, None)
        if isinstance(v, bytes):
            v = v.decode("utf-8", "replace")
        if v:
            parts.append(str(v))
    return " ".join(parts).lower()


class NodeCreateError(RuntimeError):
    pass


class GCETPUNodeProvider(NodeProvider):
    """TPU-VM provider with a real node state machine (reference:
    autoscaler/_private/gcp/node_provider.py + TPU pod handling): creates
    are issued async and retried on failure; poll() advances nodes through
    REQUESTED -> PROVISIONING -> READY by describing them, and confirms
    TERMINATING nodes actually disappeared. Command execution is injectable
    (runner(argv) -> stdout, raising on nonzero exit) so tests drive the
    lifecycle through a fake gcloud that models delays and failures."""

    def __init__(
        self,
        project: str,
        zone: str,
        accelerator_type: str = "v5litepod-8",
        runtime_version: str = "tpu-ubuntu2204-base",
        node_types: Optional[Dict[str, dict]] = None,
        runner=None,
        create_retries: int = 3,
    ):
        super().__init__(node_types)
        self.project = project
        self.zone = zone
        self.accelerator_type = accelerator_type
        self.runtime_version = runtime_version
        self._runner = runner or self._default_runner
        self.create_retries = create_retries
        # name -> {"state", "node_type", "create_attempts"}
        self._nodes: Dict[str, Dict[str, Any]] = {}

    @staticmethod
    def _default_runner(cmd: List[str]) -> str:
        import subprocess

        return subprocess.check_output(cmd, text=True)

    # -- gcloud argv ---------------------------------------------------------

    def _scope(self) -> List[str]:
        return [f"--project={self.project}", f"--zone={self.zone}"]

    def _create_cmd(self, name: str) -> List[str]:
        return [
            "gcloud", "compute", "tpus", "tpu-vm", "create", name,
            *self._scope(),
            f"--accelerator-type={self.accelerator_type}",
            f"--version={self.runtime_version}",
            "--async",
        ]

    def _delete_cmd(self, name: str) -> List[str]:
        return [
            "gcloud", "compute", "tpus", "tpu-vm", "delete", name,
            *self._scope(), "--quiet", "--async",
        ]

    def _describe_cmd(self, name: str) -> List[str]:
        return [
            "gcloud", "compute", "tpus", "tpu-vm", "describe", name,
            *self._scope(), "--format=value(state)",
        ]

    def discover_nodes(self) -> List[str]:
        """Adopt raytpu-* TPU VMs that exist in GCE but aren't tracked here
        (a fresh process running `down`, or crash recovery). Returns the
        adopted names."""
        out = self._runner(
            [
                "gcloud", "compute", "tpus", "tpu-vm", "list", *self._scope(),
                "--filter=name~^raytpu-", "--format=value(name)",
            ]
        )
        adopted = []
        for name in out.split():
            name = name.strip()
            if name and name not in self._nodes:
                self._nodes[name] = {
                    "state": READY,
                    "node_type": "unknown",
                    "create_attempts": 0,
                    "describe_misses": 0,
                }
                adopted.append(name)
        return adopted

    def run_on_node(self, name: str, command: str, worker: str = "all") -> str:
        """Run a shell command on a TPU VM over gcloud ssh (the launcher's
        head bootstrap path; reference: ray up's ssh command runner)."""
        return self._runner(
            [
                "gcloud", "compute", "tpus", "tpu-vm", "ssh", name,
                *self._scope(), f"--worker={worker}", "--command", command,
            ]
        )

    # -- lifecycle -----------------------------------------------------------

    def create_node(self, node_type: str) -> str:
        """Issue an async create; retries transient gcloud failures with the
        same name so a half-created node is adopted, not duplicated."""
        name = f"raytpu-{node_type}-{uuid.uuid4().hex[:8]}"
        attempts = 0
        last_err: Optional[Exception] = None
        while attempts < self.create_retries:
            attempts += 1
            try:
                self._runner(self._create_cmd(name))
                self._nodes[name] = {
                    "state": REQUESTED,
                    "node_type": node_type,
                    "create_attempts": attempts,
                    "describe_misses": 0,
                }
                return name
            except Exception as e:  # subprocess.CalledProcessError and kin
                msg = _error_text(e)
                if "already exists" in msg or "alreadyexists" in msg:
                    # A prior attempt was accepted server-side even though
                    # the client errored: adopt the node instead of burning
                    # retries on a non-transient error.
                    self._nodes[name] = {
                        "state": REQUESTED,
                        "node_type": node_type,
                        "create_attempts": attempts,
                        "describe_misses": 0,
                    }
                    return name
                last_err = e
                logger.warning(
                    "tpu-vm create %s attempt %d/%d failed: %r",
                    name, attempts, self.create_retries, e,
                )
        raise NodeCreateError(
            f"tpu-vm create {name} failed after {attempts} attempts"
        ) from last_err

    def terminate_node(self, provider_node_id: str) -> bool:
        """Issue an async delete. Returns False on a gcloud failure — the
        node stays tracked in its current state so the caller can retry."""
        info = self._nodes.get(provider_node_id)
        if info is None or info["state"] == TERMINATING:
            return True  # already gone / already deleting: retry is a no-op
        try:
            self._runner(self._delete_cmd(provider_node_id))
        except Exception as e:
            logger.warning("tpu-vm delete %s failed: %r", provider_node_id, e)
            return False
        if info is not None:
            info["state"] = TERMINATING
            # Fresh miss budget for the deletion phase: leftover provisioning
            # misses must not let one transient describe failure drop the
            # record of a node that may still exist and bill.
            info["describe_misses"] = 0
        return True

    def poll(self) -> None:
        """Advance the state machine by describing in-flight nodes
        (REQUESTED/PROVISIONING move toward READY; TERMINATING nodes are
        dropped once GCE stops reporting them; vanished nodes fail)."""
        for name, info in list(self._nodes.items()):
            state = info["state"]
            if state in (READY, FAILED):
                # READY needs no polling; FAILED is terminal (repair or
                # teardown decides its fate — re-describing it every round
                # costs a gcloud call and can flap behind our back).
                continue
            try:
                out = self._runner(self._describe_cmd(name)).strip().upper()
            except Exception as e:
                msg = _error_text(e)
                not_found = "not_found" in msg or "not found" in msg
                if state == TERMINATING:
                    # Only a confirmed NOT_FOUND (or repeated misses) drops
                    # the record: a transient gcloud/network failure must
                    # not silently forget a node that may still exist and
                    # bill.
                    info["describe_misses"] = info.get("describe_misses", 0) + 1
                    if not_found or info["describe_misses"] > 3:
                        del self._nodes[name]  # gone, as requested
                    continue
                # --async creates may not be describable immediately;
                # tolerate a few misses before declaring the node lost.
                info["describe_misses"] = info.get("describe_misses", 0) + 1
                if info["describe_misses"] > 3:
                    info["state"] = FAILED
                    logger.warning(
                        "tpu-vm %s vanished (describe failed %d times)",
                        name, info["describe_misses"],
                    )
                continue
            info["describe_misses"] = 0
            if state == TERMINATING:
                continue  # still deleting
            if out == "READY":
                info["state"] = READY
            elif out in ("CREATING", "STARTING", "RESTARTING", ""):
                info["state"] = PROVISIONING
            elif out in ("STOPPED", "STOPPING", "DELETING", "PREEMPTED"):
                info["state"] = FAILED

    def node_state(self, provider_node_id: str) -> Optional[str]:
        info = self._nodes.get(provider_node_id)
        return info["state"] if info else None

    def non_terminated_nodes(self) -> List[str]:
        return [
            n
            for n, info in self._nodes.items()
            if info["state"] not in (TERMINATING, FAILED)
        ]

    def ready_nodes(self) -> List[str]:
        return [
            n for n, info in self._nodes.items() if info["state"] == READY
        ]

    def failed_nodes(self) -> List[str]:
        return [
            n for n, info in self._nodes.items() if info["state"] == FAILED
        ]

    def forget_node(self, provider_node_id: str) -> None:
        """Drop a FAILED node from tracking (after gang repair)."""
        self._nodes.pop(provider_node_id, None)
