"""Node providers: pluggable node lifecycle backends.

Analog of python/ray/autoscaler/node_provider.py and the cloud
implementations under python/ray/autoscaler/_private/: a provider knows how
to create/terminate/list nodes of configured node types.
"""

from __future__ import annotations

import logging
import uuid
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


class NodeProvider:
    """Interface (reference: node_provider.py NodeProvider)."""

    def __init__(self, node_types: Optional[Dict[str, dict]] = None):
        # node_types: name -> {"resources": {...}, "min_workers", "max_workers"}
        self.node_types = node_types or {
            "worker": {"resources": {"CPU": 2.0}, "min_workers": 0, "max_workers": 4}
        }

    def create_node(self, node_type: str) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Adds/removes in-process raylets on the running cluster — the
    reference's fake_multi_node provider (autoscaler tests run against it in
    CI rather than a cloud)."""

    def __init__(self, cluster, node_types: Optional[Dict[str, dict]] = None):
        super().__init__(node_types)
        self.cluster = cluster  # ray_tpu.cluster_utils.Cluster
        self._nodes: Dict[str, Any] = {}

    def create_node(self, node_type: str) -> str:
        spec = self.node_types[node_type]
        res = dict(spec["resources"])
        node = self.cluster.add_node(
            num_cpus=res.pop("CPU", 1.0),
            num_tpus=res.pop("TPU", 0.0),
            resources=res,
        )
        pid = f"fake-{node_type}-{uuid.uuid4().hex[:6]}"
        self._nodes[pid] = node
        logger.info("fake provider launched %s (%s)", pid, spec["resources"])
        return pid

    def terminate_node(self, provider_node_id: str) -> None:
        node = self._nodes.pop(provider_node_id, None)
        if node is not None:
            self.cluster.remove_node(node)
            logger.info("fake provider terminated %s", provider_node_id)

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)

    def raylet_node_id(self, provider_node_id: str) -> Optional[str]:
        node = self._nodes.get(provider_node_id)
        return getattr(node, "node_id", None) if node is not None else None


class GCETPUNodeProvider(NodeProvider):
    """TPU-VM provider: constructs the gcloud commands for node lifecycle
    (reference: autoscaler/_private/gcp/ + tpu pod handling). Command
    execution is injectable so air-gapped tests can assert on the exact
    invocations without network access."""

    def __init__(
        self,
        project: str,
        zone: str,
        accelerator_type: str = "v5litepod-8",
        runtime_version: str = "tpu-ubuntu2204-base",
        node_types: Optional[Dict[str, dict]] = None,
        runner=None,
    ):
        super().__init__(node_types)
        self.project = project
        self.zone = zone
        self.accelerator_type = accelerator_type
        self.runtime_version = runtime_version
        self._runner = runner or self._default_runner
        self._nodes: Dict[str, str] = {}

    @staticmethod
    def _default_runner(cmd: List[str]) -> str:
        import subprocess

        return subprocess.check_output(cmd, text=True)

    def _create_cmd(self, name: str) -> List[str]:
        return [
            "gcloud", "compute", "tpus", "tpu-vm", "create", name,
            f"--project={self.project}",
            f"--zone={self.zone}",
            f"--accelerator-type={self.accelerator_type}",
            f"--version={self.runtime_version}",
        ]

    def _delete_cmd(self, name: str) -> List[str]:
        return [
            "gcloud", "compute", "tpus", "tpu-vm", "delete", name,
            f"--project={self.project}", f"--zone={self.zone}", "--quiet",
        ]

    def create_node(self, node_type: str) -> str:
        name = f"raytpu-{node_type}-{uuid.uuid4().hex[:8]}"
        self._runner(self._create_cmd(name))
        self._nodes[name] = node_type
        return name

    def terminate_node(self, provider_node_id: str) -> None:
        self._runner(self._delete_cmd(provider_node_id))
        self._nodes.pop(provider_node_id, None)

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)
