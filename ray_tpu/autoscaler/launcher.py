"""Cluster launcher: a YAML -> a running cluster (reference:
python/ray/scripts/scripts.py `ray up`/`ray down` at :1279/:1355 driving
autoscaler/_private/commands.py, schema python/ray/autoscaler/ray-schema.json).

The launcher turns a declarative cluster config into provider calls plus a
head bootstrap, then hands steady-state scaling to the Autoscaler:

    cluster_name: demo
    max_workers: 8
    idle_timeout_minutes: 5
    provider:
      type: fake | gce            # gce: + project_id / zone / runner opts
    head_node_type: head
    available_node_types:
      head:
        resources: {CPU: 4}
        min_workers: 0
        max_workers: 0
      worker:
        resources: {CPU: 4}
        min_workers: 2
        max_workers: 8

Provider `fake` boots everything in-process (cluster_utils raylets — the
reference's fake_multi_node provider pattern), which is also how the e2e
test exercises up/submit/scale/down without a cloud. Provider `gce` drives
GCETPUNodeProvider (gcloud TPU-VM lifecycle with an injectable runner) and
bootstraps the head over `gcloud ... ssh --command "ray-tpu start --head"`.
"""

from __future__ import annotations

import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.autoscaler import Autoscaler, AutoscalerConfig
from ray_tpu.autoscaler.node_provider import (
    FakeNodeProvider,
    GCETPUNodeProvider,
)

logger = logging.getLogger(__name__)

_STATE_DIR = os.path.expanduser("~/.ray_tpu")


class ClusterConfigError(ValueError):
    pass


@dataclass
class ClusterConfig:
    """Validated cluster YAML (reference schema: ray-schema.json)."""

    cluster_name: str
    provider: Dict[str, Any]
    head_node_type: str
    available_node_types: Dict[str, Dict[str, Any]]
    max_workers: int = 8
    idle_timeout_minutes: float = 5.0
    raw: Dict[str, Any] = field(default_factory=dict)

    REQUIRED = ("cluster_name", "provider", "head_node_type", "available_node_types")

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ClusterConfig":
        for key in cls.REQUIRED:
            if key not in d:
                raise ClusterConfigError(f"cluster config missing '{key}'")
        if not isinstance(d["available_node_types"], dict) or not d[
            "available_node_types"
        ]:
            raise ClusterConfigError("available_node_types must be a non-empty map")
        if d["head_node_type"] not in d["available_node_types"]:
            raise ClusterConfigError(
                f"head_node_type {d['head_node_type']!r} not in available_node_types"
            )
        ptype = (d.get("provider") or {}).get("type")
        if ptype not in ("fake", "gce"):
            raise ClusterConfigError(
                f"provider.type must be 'fake' or 'gce', got {ptype!r}"
            )
        for name, spec in d["available_node_types"].items():
            if "resources" not in spec:
                raise ClusterConfigError(f"node type {name!r} missing resources")
            if int(spec.get("min_workers", 0)) > int(
                spec.get("max_workers", d.get("max_workers", 8))
            ):
                raise ClusterConfigError(
                    f"node type {name!r}: min_workers > max_workers"
                )
        return cls(
            cluster_name=str(d["cluster_name"]),
            provider=dict(d["provider"]),
            head_node_type=str(d["head_node_type"]),
            available_node_types={
                k: dict(v) for k, v in d["available_node_types"].items()
            },
            max_workers=int(d.get("max_workers", 8)),
            idle_timeout_minutes=float(d.get("idle_timeout_minutes", 5.0)),
            raw=dict(d),
        )

    @classmethod
    def from_yaml(cls, path: str) -> "ClusterConfig":
        import yaml

        with open(path) as f:
            d = yaml.safe_load(f)
        if not isinstance(d, dict):
            raise ClusterConfigError(f"{path} is not a YAML mapping")
        return cls.from_dict(d)

    def worker_types(self) -> Dict[str, Dict[str, Any]]:
        return {
            k: v
            for k, v in self.available_node_types.items()
            if k != self.head_node_type
        }


def _state_path(name: str) -> str:
    return os.path.join(_STATE_DIR, f"cluster-{name}.json")


class ClusterLauncher:
    """up/down/submit for one cluster config.

    For provider 'fake' the head and workers are in-process raylets; for
    'gce' nodes are TPU VMs and bootstrap runs through the provider's
    injectable command runner (tests inject a fake gcloud).
    """

    def __init__(self, config: ClusterConfig, runner=None):
        self.config = config
        self._runner = runner  # gce: injectable gcloud runner
        self.provider = None
        self.autoscaler: Optional[Autoscaler] = None
        self.head_address: Optional[str] = None
        self._fake_cluster = None
        self._head_pid: Optional[str] = None
        self._worker_pids: List[str] = []

    # -- up ------------------------------------------------------------------

    def up(self) -> str:
        """Boot head + min_workers; returns the head address."""
        cfg = self.config
        self._make_provider()
        self._bootstrap_head()
        # Initial workers: honor per-type min_workers at launch (the
        # autoscaler keeps them there afterwards).
        for ntype, spec in cfg.worker_types().items():
            for _ in range(int(spec.get("min_workers", 0))):
                self._worker_pids.append(self.provider.create_node(ntype))
        self._wait_ready()
        self.autoscaler = Autoscaler(
            self.provider,
            AutoscalerConfig(
                idle_timeout_s=cfg.idle_timeout_minutes * 60.0,
            ),
        )
        # Adopt the launch-time workers so idle-timeout/min-worker
        # accounting sees them.
        for pid in self._worker_pids:
            self._adopt(pid)
        self._write_state()
        logger.info(
            "cluster %s up: head=%s workers=%d",
            cfg.cluster_name, self.head_address, len(self._worker_pids),
        )
        return self.head_address

    def _adopt(self, pid: str) -> None:
        from ray_tpu.autoscaler.autoscaler import _NodeTracker

        ntype = self._pid_type(pid)
        self.autoscaler._tracked[pid] = _NodeTracker(
            provider_node_ids=[pid], node_type=ntype
        )

    def _pid_type(self, pid: str) -> str:
        # Fake pids embed the type; gce names embed it too (raytpu-<type>-).
        for ntype in self.config.available_node_types:
            if f"-{ntype}-" in pid or pid.startswith(f"fake-{ntype}"):
                return ntype
        return next(iter(self.config.worker_types()), self.config.head_node_type)

    def _make_provider(self) -> None:
        cfg = self.config
        ptype = cfg.provider["type"]
        node_types = cfg.available_node_types
        if ptype == "fake":
            import ray_tpu
            from ray_tpu.cluster_utils import Cluster

            head_res = dict(node_types[cfg.head_node_type]["resources"])
            self._fake_cluster = Cluster(
                initialize_head=True,
                head_node_args={
                    "num_cpus": head_res.pop("CPU", 1.0),
                    "num_tpus": head_res.pop("TPU", 0.0),
                    "resources": head_res,
                },
            )
            self.provider = FakeNodeProvider(
                self._fake_cluster, node_types=node_types
            )
        else:
            kwargs = {
                k: v
                for k, v in cfg.provider.items()
                if k in ("project", "zone", "accelerator_type", "runtime_version")
            }
            self.provider = GCETPUNodeProvider(
                node_types=node_types, runner=self._runner, **kwargs
            )

    def _bootstrap_head(self) -> None:
        cfg = self.config
        if cfg.provider["type"] == "fake":
            host, port = self._fake_cluster.gcs_addr
            self.head_address = f"{host}:{port}"
            return
        # GCE: create the head TPU-VM, then start the head daemon over ssh
        # (reference: ray up's "head_start_ray_commands" over ssh).
        self._head_pid = self.provider.create_node(cfg.head_node_type)
        deadline = time.monotonic() + float(
            cfg.provider.get("head_ready_timeout_s", 600)
        )
        while self.provider.node_state(self._head_pid) != "READY":
            self.provider.poll()
            if self.provider.node_state(self._head_pid) == "FAILED":
                raise RuntimeError("head node failed to provision")
            if time.monotonic() > deadline:
                raise TimeoutError("head node not READY before timeout")
            time.sleep(cfg.provider.get("poll_interval_s", 2.0))
        self.provider.run_on_node(
            self._head_pid,
            cfg.provider.get(
                "head_start_command", "ray-tpu start --head --port 6379"
            ),
        )
        self.head_address = f"{self._head_pid}:6379"

    def _wait_ready(self, timeout: float = 600.0) -> None:
        """Wait until every launched worker is usable (fake: immediate;
        gce: REQUESTED/PROVISIONING -> READY via poll)."""
        if self.config.provider["type"] == "fake":
            return
        deadline = time.monotonic() + timeout
        while True:
            self.provider.poll()
            states = [self.provider.node_state(p) for p in self._worker_pids]
            if all(s == "READY" for s in states):
                return
            if time.monotonic() > deadline:
                raise TimeoutError(f"workers not READY: {states}")
            time.sleep(self.config.provider.get("poll_interval_s", 2.0))

    # -- steady state --------------------------------------------------------

    def update(self) -> Dict[str, int]:
        """One autoscaler round (callers loop this; the CLI runs it in a
        monitor loop)."""
        assert self.autoscaler is not None, "cluster is not up"
        return self.autoscaler.update()

    # -- submit --------------------------------------------------------------

    def submit(self, entrypoint: str, wait: bool = True, timeout: float = 300.0):
        """Submit a job entrypoint to the running cluster's job manager."""
        from ray_tpu.job import JobSubmissionClient

        client = JobSubmissionClient(self.head_address)
        sid = client.submit_job(entrypoint=entrypoint)
        if not wait:
            return sid, None
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            info = client.get_job_info(sid)
            if info.status in ("SUCCEEDED", "FAILED", "STOPPED"):
                return sid, info
            time.sleep(0.2)
        raise TimeoutError(f"job {sid} did not finish within {timeout}s")

    # -- down ----------------------------------------------------------------

    def down(self) -> None:
        cfg = self.config
        for pid in list(self.provider.non_terminated_nodes()):
            try:
                self.provider.terminate_node(pid)
            except Exception:
                logger.exception("terminate of %s failed", pid)
        if self._head_pid is not None:
            try:
                self.provider.terminate_node(self._head_pid)
            except Exception:
                logger.exception("terminate of head failed")
        if self._fake_cluster is not None:
            self._fake_cluster.shutdown()
            self._fake_cluster = None
        try:
            os.unlink(_state_path(cfg.cluster_name))
        except OSError:
            pass
        logger.info("cluster %s down", cfg.cluster_name)

    # -- state file ----------------------------------------------------------

    def _write_state(self) -> None:
        os.makedirs(_STATE_DIR, exist_ok=True)
        with open(_state_path(self.config.cluster_name), "w") as f:
            json.dump(
                {
                    "cluster_name": self.config.cluster_name,
                    "head_address": self.head_address,
                    "provider_type": self.config.provider["type"],
                    "worker_pids": self._worker_pids,
                },
                f,
            )


def read_cluster_state(name: str) -> Optional[dict]:
    try:
        with open(_state_path(name)) as f:
            return json.load(f)
    except OSError:
        return None
