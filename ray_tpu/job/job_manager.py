"""Job manager: supervisor actor + submission client.

Analog of dashboard/modules/job/job_manager.py (JobManager:56) and
job_supervisor.py (JobSupervisor:49): the supervisor is a detached actor so
the job outlives the submitting client; logs and JobInfo live in the GCS KV.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

JOB_INFO_NS = "job_info"
JOB_LOGS_NS = "job_logs"
MAX_LOG_BYTES = 4 * 1024 * 1024


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@dataclass
class JobInfo:
    submission_id: str
    entrypoint: str
    status: str = JobStatus.PENDING
    message: str = ""
    start_time: float = field(default_factory=time.time)
    end_time: Optional[float] = None
    metadata: Dict[str, str] = field(default_factory=dict)
    runtime_env: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def from_json(cls, blob: bytes) -> "JobInfo":
        return cls(**json.loads(blob))


class JobSupervisor:
    """Detached actor running one job's entrypoint as a subprocess."""

    def __init__(self, submission_id: str, entrypoint: str, info_json: bytes):
        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self.info = JobInfo.from_json(info_json)
        self.proc = None
        self._stopped = False

    async def _kv_put(self, ns: str, key: str, value: bytes) -> None:
        from ray_tpu._private import worker as worker_mod

        core = worker_mod._core()
        await core.gcs.kv_put(key, value, ns=ns)

    async def _set_status(self, status: str, message: str = "") -> None:
        self.info.status = status
        self.info.message = message
        if status in JobStatus.TERMINAL:
            self.info.end_time = time.time()
        await self._kv_put(JOB_INFO_NS, self.submission_id, self.info.to_json())

    async def run(self) -> str:
        """Run the entrypoint to completion; returns final status."""
        import asyncio

        from ray_tpu._private import worker as worker_mod

        core = worker_mod._core()
        env = dict(os.environ)
        # The job's own driver connects to this same cluster.
        gcs_host, gcs_port = core.gcs.conn.peername
        env["RAY_TPU_ADDRESS"] = f"{gcs_host}:{gcs_port}"
        env.update(self.info.runtime_env.get("env_vars") or {})
        cwd = self.info.runtime_env.get("working_dir") or None

        await self._set_status(JobStatus.RUNNING)
        log_buf = bytearray()
        last_flush = 0.0
        try:
            self.proc = await asyncio.create_subprocess_shell(
                self.entrypoint,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.STDOUT,
                env=env,
                cwd=cwd,
            )
            assert self.proc.stdout is not None
            while True:
                line = await self.proc.stdout.readline()
                if not line:
                    break
                log_buf.extend(line)
                if len(log_buf) > MAX_LOG_BYTES:
                    del log_buf[: len(log_buf) - MAX_LOG_BYTES]
                # Throttled flush: pushing the whole buffer per line would be
                # O(lines x buffer) KV traffic for chatty jobs.
                now = time.monotonic()
                if now - last_flush >= 1.0:
                    last_flush = now
                    await self._kv_put(
                        JOB_LOGS_NS, self.submission_id, bytes(log_buf)
                    )
            code = await self.proc.wait()
            if self._stopped:
                await self._set_status(JobStatus.STOPPED, "stopped by user")
            elif code == 0:
                await self._set_status(JobStatus.SUCCEEDED)
            else:
                await self._set_status(JobStatus.FAILED, f"exit code {code}")
        except Exception as e:  # noqa: BLE001
            await self._set_status(JobStatus.FAILED, f"{type(e).__name__}: {e}")
        finally:
            await self._kv_put(JOB_LOGS_NS, self.submission_id, bytes(log_buf))
        return self.info.status

    async def stop(self) -> bool:
        self._stopped = True
        if self.proc is not None and self.proc.returncode is None:
            try:
                self.proc.terminate()
            except ProcessLookupError:
                pass
        return True

    async def ping(self) -> str:
        return "pong"


class JobSubmissionClient:
    """Analog of the reference SDK (dashboard/modules/job/sdk.py), talking
    directly to the cluster instead of through the dashboard REST layer."""

    def __init__(self, address: Optional[str] = None):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address) if address else ray_tpu.init()
        self._ray = ray_tpu

    def _kv_get(self, ns: str, key: str) -> Optional[bytes]:
        from ray_tpu._private import worker as worker_mod

        core = worker_mod._core()
        return worker_mod.global_worker.run_async(core.gcs.kv_get(key, ns=ns))

    def _kv_keys(self, ns: str) -> List[str]:
        from ray_tpu._private import worker as worker_mod

        core = worker_mod._core()
        return worker_mod.global_worker.run_async(core.gcs.kv_keys("", ns=ns))

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: Optional[str] = None,
        runtime_env: Optional[Dict[str, Any]] = None,
        metadata: Optional[Dict[str, str]] = None,
    ) -> str:
        submission_id = submission_id or f"raytpu-job-{uuid.uuid4().hex[:10]}"
        info = JobInfo(
            submission_id=submission_id,
            entrypoint=entrypoint,
            runtime_env=runtime_env or {},
            metadata=metadata or {},
        )
        from ray_tpu._private import worker as worker_mod

        core = worker_mod._core()
        worker_mod.global_worker.run_async(
            core.gcs.kv_put(submission_id, info.to_json(), ns=JOB_INFO_NS)
        )
        supervisor = (
            self._ray.remote(JobSupervisor)
            .options(
                name=f"_job_supervisor:{submission_id}",
                namespace="_job",
                lifetime="detached",
                max_concurrency=4,
                num_cpus=0.1,
            )
            .remote(submission_id, entrypoint, info.to_json())
        )
        # Fire-and-forget; the returned ref resolves when the job finishes.
        supervisor.run.remote()
        return submission_id

    def get_job_status(self, submission_id: str) -> str:
        return self.get_job_info(submission_id).status

    def get_job_info(self, submission_id: str) -> JobInfo:
        blob = self._kv_get(JOB_INFO_NS, submission_id)
        if blob is None:
            raise ValueError(f"no job {submission_id!r}")
        return JobInfo.from_json(blob)

    def get_job_logs(self, submission_id: str) -> str:
        blob = self._kv_get(JOB_LOGS_NS, submission_id)
        return (blob or b"").decode(errors="replace")

    def list_jobs(self) -> List[JobInfo]:
        out = []
        for key in self._kv_keys(JOB_INFO_NS):
            blob = self._kv_get(JOB_INFO_NS, key)
            if blob:
                out.append(JobInfo.from_json(blob))
        out.sort(key=lambda j: j.start_time)
        return out

    def stop_job(self, submission_id: str) -> bool:
        try:
            sup = self._ray.get_actor(
                f"_job_supervisor:{submission_id}", namespace="_job"
            )
        except ValueError:
            return False
        return self._ray.get(sup.stop.remote())

    def wait_until_finish(
        self, submission_id: str, timeout_s: float = 300.0, poll_s: float = 0.5
    ) -> str:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            status = self.get_job_status(submission_id)
            if status in JobStatus.TERMINAL:
                return status
            time.sleep(poll_s)
        raise TimeoutError(f"job {submission_id} still {status} after {timeout_s}s")
