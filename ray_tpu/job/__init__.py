"""ray_tpu.job: job submission (reference: dashboard/modules/job).

A submitted job = a detached JobSupervisor actor that runs the entrypoint as
a subprocess, streams its output into the GCS KV, and records JobInfo status
transitions (PENDING -> RUNNING -> SUCCEEDED/FAILED/STOPPED), mirroring
dashboard/modules/job/job_manager.py:56 + job_supervisor.py:49.
"""

from ray_tpu.job.job_manager import (
    JobInfo,
    JobStatus,
    JobSubmissionClient,
)

__all__ = ["JobInfo", "JobStatus", "JobSubmissionClient"]
