"""In-process multi-node cluster harness for tests.

Analog of python/ray/cluster_utils.py:135: boots one GCS plus N raylets inside
one machine — the backbone of "distributed" tests without real hosts. Each
added node is a full raylet (own worker pool, own object store namespace) on
the driver's background event loop; killing a node drops its RPC links, which
exercises the same death paths as a real host failure.
"""

from __future__ import annotations

import asyncio
from typing import Dict, Optional

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.node import Node
from ray_tpu._private.raylet import Raylet


class Cluster:
    def __init__(self, initialize_head: bool = True, head_node_args: Optional[dict] = None):
        from ray_tpu._private.common import config

        config.refresh()  # pick up env overrides set after import (fixtures)
        self._w = worker_mod.global_worker
        if self._w.loop is None:
            self._w._start_loop()
        self.gcs_server: Optional[GcsServer] = None
        self.gcs_addr = None
        self.raylets: Dict[str, Raylet] = {}
        self.head_node: Optional[Node] = None
        if initialize_head:
            self._start_head(head_node_args or {})

    def _run(self, coro, timeout=60):
        return self._w.run_async(coro, timeout=timeout)

    def _start_head(self, args: dict) -> None:
        async def go():
            node = Node(head=True, **args)
            await node.start()
            return node

        node = self._run(go())
        self.head_node = node
        self.gcs_server = node.gcs_server
        self.gcs_addr = node.gcs_addr
        self.raylets[node.raylet.node_id] = node.raylet

    @property
    def address(self) -> str:
        return f"{self.gcs_addr[0]}:{self.gcs_addr[1]}"

    def add_node(
        self,
        num_cpus: float = 1,
        num_tpus: float = 0,
        resources: Optional[Dict[str, float]] = None,
        object_store_memory: Optional[int] = None,
        labels: Optional[Dict[str, str]] = None,
    ) -> Raylet:
        res = dict(resources or {})
        res["CPU"] = float(num_cpus)
        if num_tpus:
            res["TPU"] = float(num_tpus)

        async def go():
            raylet = Raylet(
                self.gcs_addr,
                self.head_node.session_name,
                resources=res,
                object_store_memory=object_store_memory,
                labels=labels,
            )
            await raylet.start()
            return raylet

        raylet = self._run(go())
        self.raylets[raylet.node_id] = raylet
        return raylet

    def remove_node(self, raylet: Raylet) -> None:
        """Simulates node death: kills workers and drops the GCS link."""
        self.raylets.pop(raylet.node_id, None)

        async def go():
            await raylet.stop()

        self._run(go())

    def connect(self, **init_kwargs):
        """Attach the current process as a driver to this cluster."""
        import ray_tpu

        return ray_tpu.init(address=self.address, **init_kwargs)

    def shutdown(self) -> None:
        import ray_tpu

        raylets = list(self.raylets.values())
        self.raylets.clear()

        async def go():
            for r in raylets:
                try:
                    await r.stop()
                except Exception:
                    pass
            # HA mode: disarm the warm standby BEFORE stopping the GCS, or
            # the expired lease promotes a new leader into the dying cluster.
            standby = getattr(self.head_node, "gcs_standby", None)
            if standby is not None:
                if standby.server is self.gcs_server:
                    standby.server = None
                try:
                    await standby.stop()
                except Exception:
                    pass
                self.head_node.gcs_standby = None
            if self.gcs_server is not None:
                await self.gcs_server.stop()

        if self._w.loop is not None:
            try:
                self._run(go())
            except Exception:
                pass
        # Driver teardown last: its farewell RPCs fail fast against the
        # now-stopped daemons and the loop is reclaimed here.
        if worker_mod.global_worker.connected:
            ray_tpu.shutdown()
