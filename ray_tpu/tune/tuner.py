"""Tuner + TuneConfig + ResultGrid (reference: python/ray/tune/tuner.py:44,
tune/result_grid.py)."""

from __future__ import annotations

import os
import uuid
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.air.config import Result, RunConfig
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.search import BasicVariantGenerator
from ray_tpu.tune.tune_controller import (
    ERROR,
    Trial,
    TuneController,
    new_trial_id,
)


@dataclass
class TuneConfig:
    """reference: tune/tune_config.py."""

    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Optional[TrialScheduler] = None
    search_alg: Optional[Any] = None
    seed: Optional[int] = None

    def __post_init__(self):
        if self.mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")


class ResultGrid:
    """reference: tune/result_grid.py ResultGrid."""

    def __init__(self, results: List[Result], trials: List[Trial]):
        self._results = results
        self._trials = trials

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    @property
    def errors(self):
        return [r.error for r in self._results if r.error is not None]

    @property
    def num_errors(self) -> int:
        return len(self.errors)

    def get_best_result(
        self, metric: Optional[str] = None, mode: Optional[str] = None
    ) -> Result:
        metric = metric or getattr(self, "_default_metric", None)
        mode = mode or getattr(self, "_default_mode", "max")
        if metric is None:
            raise ValueError("metric is required (none set in TuneConfig)")
        sign = 1.0 if mode == "max" else -1.0
        scored = [
            r
            for r in self._results
            if r.metrics is not None and metric in r.metrics
        ]
        if not scored:
            raise RuntimeError(f"no trial reported metric {metric!r}")
        return max(scored, key=lambda r: sign * float(r.metrics[metric]))

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([r.metrics or {} for r in self._results])


def _with_resources_of(trainable) -> Dict[str, float]:
    return getattr(trainable, "_tune_resources", None) or {"CPU": 1.0}


def with_resources(trainable: Callable, resources: Dict[str, float]):
    """reference: tune/trainable/util.py with_resources."""
    if isinstance(trainable, type):
        # Subclass instead of mutating: the same Trainable class may be used
        # with different resources by different Tuners.
        return type(
            trainable.__name__,
            (trainable,),
            {"_tune_resources": dict(resources)},
        )

    def wrapped(config):
        return trainable(config)

    wrapped.__name__ = getattr(trainable, "__name__", "trainable")
    wrapped._tune_resources = dict(resources)
    return wrapped


class Tuner:
    """reference: tune/tuner.py:44; fit() at :344."""

    def __init__(
        self,
        trainable: Callable,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
        _trials: Optional[List[Trial]] = None,
    ):
        from ray_tpu.train.base_trainer import BaseTrainer
        from ray_tpu.tune.trainable import Trainable, class_trainable_to_fn

        if isinstance(trainable, BaseTrainer):
            self._trainer = trainable
            trainable = trainable.as_trainable()
        else:
            self._trainer = None
            if isinstance(trainable, type) and issubclass(trainable, Trainable):
                trainable = class_trainable_to_fn(trainable)
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._preloaded_trials = _trials

    def _experiment_layout(self):
        name = self.run_config.name or (
            f"{getattr(self.trainable, '__name__', 'exp')}_{uuid.uuid4().hex[:8]}"
        )
        storage = self.run_config.resolved_storage_path()
        exp_dir = os.path.join(storage, name)
        os.makedirs(exp_dir, exist_ok=True)
        return name, storage, exp_dir

    def fit(self) -> ResultGrid:
        name, storage, exp_dir = self._experiment_layout()
        searcher = None
        if self._preloaded_trials is not None:
            trials = self._preloaded_trials
        else:
            from ray_tpu.tune.suggest import Searcher

            search = self.tune_config.search_alg or BasicVariantGenerator(
                self.tune_config.seed
            )
            if isinstance(search, Searcher):
                # Sequential suggest/observe searcher (TPE etc.): trials are
                # created on demand inside the controller so completed
                # results can steer later suggestions. Cohort schedulers are
                # incompatible with on-demand creation: synchronous
                # HyperBand fixes rung membership up front (late adds join
                # already-closed rungs), and PBT's exploit mutates configs
                # behind the searcher's back, poisoning its model.
                from ray_tpu.tune.schedulers import (
                    HyperBandScheduler,
                    PopulationBasedTraining,
                )

                if isinstance(
                    self.tune_config.scheduler,
                    (HyperBandScheduler, PopulationBasedTraining),
                ):
                    raise ValueError(
                        "search_alg searchers cannot be combined with "
                        "synchronous HyperBand or PBT; use ASHA, median "
                        "stopping, or the default FIFO scheduler"
                    )
                search.set_search_space(self.param_space)
                search.set_metric(self.tune_config.metric, self.tune_config.mode)
                searcher = search
                trials = []
            else:
                configs = search.generate(
                    self.param_space, self.tune_config.num_samples
                )
                trials = [
                    Trial(trial_id=new_trial_id(), config=c) for c in configs
                ]
        scheduler = self.tune_config.scheduler
        if scheduler is not None:
            scheduler.set_metric(self.tune_config.metric, self.tune_config.mode)
        controller = TuneController(
            self.trainable,
            trials,
            experiment_name=name,
            experiment_dir=exp_dir,
            storage_path=storage,
            scheduler=scheduler,
            max_concurrent=self.tune_config.max_concurrent_trials,
            resources_per_trial=_with_resources_of(self.trainable),
            searcher=searcher,
            num_samples=self.tune_config.num_samples,
        )
        controller.metric = self.tune_config.metric
        controller.mode = self.tune_config.mode
        controller.stop_criteria = self.run_config.stop
        controller.run()
        results = [
            Result(
                metrics=t.last_result,
                checkpoint=Checkpoint(t.checkpoint_path)
                if t.checkpoint_path
                else None,
                path=os.path.join(exp_dir, t.trial_id),
                error=RuntimeError(t.error) if t.status == ERROR else None,
                metrics_history=t.history,
            )
            for t in trials
        ]
        grid = ResultGrid(results, trials)
        grid._default_metric = self.tune_config.metric
        grid._default_mode = self.tune_config.mode
        return grid

    @classmethod
    def restore(
        cls,
        path: str,
        trainable: Callable,
        *,
        resume_errored: bool = False,
        tune_config: Optional[TuneConfig] = None,
    ) -> "Tuner":
        """Rebuild a Tuner from an experiment dir; finished trials keep their
        results, unfinished (and optionally errored) ones re-run
        (reference: tuner.py Tuner.restore)."""
        state = TuneController.load_state(path)
        trials = []
        for ts in state["trials"]:
            t = Trial(
                trial_id=ts["trial_id"],
                config=ts["config"],
                history=ts["history"],
                checkpoint_path=ts["checkpoint_path"],
                error=ts["error"],
                early_stopped=ts["early_stopped"],
                status=ts["status"],
            )
            if t.status not in ("TERMINATED",) and not (
                t.status == ERROR and not resume_errored
            ):
                t.status = "PENDING"
            trials.append(t)
        run_config = RunConfig(
            name=os.path.basename(path), storage_path=os.path.dirname(path)
        )
        if tune_config is None and state.get("metric") is not None:
            tune_config = TuneConfig(
                metric=state["metric"], mode=state.get("mode") or "max"
            )
        return cls(
            trainable,
            tune_config=tune_config,
            run_config=run_config,
            _trials=trials,
        )
