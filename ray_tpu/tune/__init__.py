"""ray_tpu.tune — hyperparameter search and trial execution (reference:
python/ray/tune)."""

from typing import Any, Dict, Optional

from ray_tpu.tune.schedulers import (
    AsyncHyperBandScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.trainable import Trainable
from ray_tpu.tune.search import (
    BasicVariantGenerator,
    choice,
    grid_search,
    loguniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.suggest import Searcher, TPESearcher
from ray_tpu.tune.tuner import ResultGrid, TuneConfig, Tuner, with_resources

ASHAScheduler = AsyncHyperBandScheduler


def report(
    metrics: Dict[str, Any],
    *,
    checkpoint=None,
    _already_persisted: bool = False,
) -> None:
    """Report from inside a trial (reference: ray.tune.report / ray.train.report
    are the same session under the hood)."""
    from ray_tpu.train import _session
    from ray_tpu.train._checkpoint import Checkpoint
    from ray_tpu.train._session import TrainingResult

    s = _session._get_session()
    if checkpoint is not None and _already_persisted:
        s.latest_checkpoint = (
            checkpoint
            if isinstance(checkpoint, Checkpoint)
            else Checkpoint(checkpoint)
        )
        s.result_queue.put(
            TrainingResult(
                metrics=dict(metrics),
                checkpoint_path=s.latest_checkpoint.path,
                iteration=s.iteration,
                world_rank=s.world_rank,
            )
        )
        s.iteration += 1
    else:
        s.report(metrics, checkpoint)


def get_checkpoint():
    from ray_tpu.train import _session

    return _session._get_session().get_checkpoint()


__all__ = [
    "Tuner",
    "TuneConfig",
    "ResultGrid",
    "with_resources",
    "report",
    "get_checkpoint",
    "uniform",
    "loguniform",
    "randint",
    "choice",
    "sample_from",
    "grid_search",
    "BasicVariantGenerator",
    "TrialScheduler",
    "FIFOScheduler",
    "AsyncHyperBandScheduler",
    "ASHAScheduler",
    "HyperBandScheduler",
    "PopulationBasedTraining",
    "MedianStoppingRule",
    "Trainable",
    "Searcher",
    "TPESearcher",
]
