"""Trial schedulers (reference: python/ray/tune/schedulers — FIFO,
async_hyperband.py ASHA, median_stopping_rule.py).

Schedulers see every reported result and decide CONTINUE or STOP; the
controller enforces the decision by tearing down the trial actor.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def set_metric(self, metric: Optional[str], mode: Optional[str]) -> None:
        if getattr(self, "metric", None) is None:
            self.metric = metric
        if getattr(self, "mode", None) is None:
            self.mode = mode

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str, result: Optional[Dict[str, Any]]) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion."""


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: tune/schedulers/async_hyperband.py).

    Rungs at t = grace_period * reduction_factor**k up to max_t. When a trial
    reaches a rung it is compared against the top 1/reduction_factor quantile
    of everything recorded at that rung; below the cutoff → STOP. Async: no
    waiting for a full rung cohort.
    """

    def __init__(
        self,
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        time_attr: str = "training_iteration",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: float = 4,
    ):
        self.metric, self.mode = metric, mode
        self.time_attr = time_attr
        self.max_t, self.grace = max_t, grace_period
        self.rf = reduction_factor
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(int(t))
            t *= reduction_factor
        # rung milestone -> recorded metric values of trials that reached it
        self.recorded: Dict[int, Dict[str, float]] = collections.defaultdict(dict)
        self._next_rung: Dict[str, int] = {}  # trial -> index into rungs

    def _sign(self) -> float:
        return 1.0 if (self.mode or "max") == "max" else -1.0

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        metric = result.get(self.metric)
        if metric is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        idx = self._next_rung.setdefault(trial_id, 0)
        decision = CONTINUE
        while idx < len(self.rungs) and t >= self.rungs[idx]:
            milestone = self.rungs[idx]
            rung = self.recorded[milestone]
            rung[trial_id] = self._sign() * float(metric)
            vals = sorted(rung.values(), reverse=True)
            cutoff_n = max(1, int(len(vals) / self.rf))
            cutoff = vals[cutoff_n - 1]
            if rung[trial_id] < cutoff:
                decision = STOP
            idx += 1
        self._next_rung[trial_id] = idx
        return decision


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running-average metric falls below the median of
    all trials' averages at the same step (reference:
    tune/schedulers/median_stopping_rule.py)."""

    def __init__(
        self,
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        time_attr: str = "training_iteration",
        grace_period: int = 1,
        min_samples_required: int = 3,
    ):
        self.metric, self.mode = metric, mode
        self.time_attr = time_attr
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._history: Dict[str, List[float]] = collections.defaultdict(list)

    def _sign(self) -> float:
        return 1.0 if (self.mode or "max") == "max" else -1.0

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        metric = result.get(self.metric)
        if metric is None:
            return CONTINUE
        self._history[trial_id].append(self._sign() * float(metric))
        if t < self.grace or len(self._history) < self.min_samples:
            return CONTINUE
        averages = {
            tid: sum(h) / len(h) for tid, h in self._history.items() if h
        }
        vals = sorted(averages.values())
        median = vals[len(vals) // 2]
        if averages[trial_id] < median:
            return STOP
        return CONTINUE
