"""Trial schedulers (reference: python/ray/tune/schedulers — FIFO,
async_hyperband.py ASHA, median_stopping_rule.py).

Schedulers see every reported result and decide CONTINUE or STOP; the
controller enforces the decision by tearing down the trial actor.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def set_metric(self, metric: Optional[str], mode: Optional[str]) -> None:
        if getattr(self, "metric", None) is None:
            self.metric = metric
        if getattr(self, "mode", None) is None:
            self.mode = mode

    def on_trial_add(self, trial_id: str) -> None:
        """Called once per trial before the experiment starts (reference:
        TrialScheduler.on_trial_add) — lets cohort-based schedulers fix
        membership up front instead of discovering trials lazily."""

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str, result: Optional[Dict[str, Any]]) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion."""


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: tune/schedulers/async_hyperband.py).

    Rungs at t = grace_period * reduction_factor**k up to max_t. When a trial
    reaches a rung it is compared against the top 1/reduction_factor quantile
    of everything recorded at that rung; below the cutoff → STOP. Async: no
    waiting for a full rung cohort.
    """

    def __init__(
        self,
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        time_attr: str = "training_iteration",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: float = 4,
    ):
        self.metric, self.mode = metric, mode
        self.time_attr = time_attr
        self.max_t, self.grace = max_t, grace_period
        self.rf = reduction_factor
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(int(t))
            t *= reduction_factor
        # rung milestone -> recorded metric values of trials that reached it
        self.recorded: Dict[int, Dict[str, float]] = collections.defaultdict(dict)
        self._next_rung: Dict[str, int] = {}  # trial -> index into rungs

    def _sign(self) -> float:
        return 1.0 if (self.mode or "max") == "max" else -1.0

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        metric = result.get(self.metric)
        if metric is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        idx = self._next_rung.setdefault(trial_id, 0)
        decision = CONTINUE
        while idx < len(self.rungs) and t >= self.rungs[idx]:
            milestone = self.rungs[idx]
            rung = self.recorded[milestone]
            rung[trial_id] = self._sign() * float(metric)
            vals = sorted(rung.values(), reverse=True)
            cutoff_n = max(1, int(len(vals) / self.rf))
            cutoff = vals[cutoff_n - 1]
            if rung[trial_id] < cutoff:
                decision = STOP
            idx += 1
        self._next_rung[trial_id] = idx
        return decision


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running-average metric falls below the median of
    all trials' averages at the same step (reference:
    tune/schedulers/median_stopping_rule.py)."""

    def __init__(
        self,
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        time_attr: str = "training_iteration",
        grace_period: int = 1,
        min_samples_required: int = 3,
    ):
        self.metric, self.mode = metric, mode
        self.time_attr = time_attr
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._history: Dict[str, List[float]] = collections.defaultdict(list)

    def _sign(self) -> float:
        return 1.0 if (self.mode or "max") == "max" else -1.0

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        metric = result.get(self.metric)
        if metric is None:
            return CONTINUE
        self._history[trial_id].append(self._sign() * float(metric))
        if t < self.grace or len(self._history) < self.min_samples:
            return CONTINUE
        averages = {
            tid: sum(h) / len(h) for tid, h in self._history.items() if h
        }
        vals = sorted(averages.values())
        median = vals[len(vals) // 2]
        if averages[trial_id] < median:
            return STOP
        return CONTINUE


# Extended decisions (beyond CONTINUE/STOP): tuple decisions carry a payload.
PAUSE = "PAUSE"
EXPLOIT = "EXPLOIT"  # ("EXPLOIT", new_config, donor_checkpoint_path)
RESUME = "RESUME"
COMPLETE = "COMPLETE"  # trial used its full budget: stop WITHOUT early_stopped


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: tune/schedulers/pbt.py PopulationBasedTraining).

    Every `perturbation_interval` units of `time_attr`, a trial in the
    bottom `quantile_fraction` of the population EXPLOITS a trial from the
    top quantile: it adopts the donor's latest checkpoint and a mutated copy
    of the donor's config (explore step), then continues training in place.
    Requires trainables that report with checkpoints — the fork is a
    checkpoint restore.

    hyperparam_mutations: {key: list | (low, high) tuple | callable}. The
    explore step resamples the key with `resample_probability`, otherwise
    multiplies numeric values by 0.8 or 1.2 (the reference's default
    perturbation factors).
    """

    def __init__(
        self,
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        time_attr: str = "training_iteration",
        perturbation_interval: int = 4,
        hyperparam_mutations: Optional[Dict[str, Any]] = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        seed: Optional[int] = None,
    ):
        import random

        if not hyperparam_mutations:
            raise ValueError("hyperparam_mutations must be a non-empty dict")
        if not 0.0 < quantile_fraction <= 0.5:
            raise ValueError("quantile_fraction must be in (0, 0.5]")
        self.metric, self.mode = metric, mode
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._scores: Dict[str, float] = {}  # trial -> latest signed score
        self._last_perturb: Dict[str, int] = {}
        self._trial_reader = None  # injected by the controller
        self.num_perturbations = 0

    def set_trial_state_reader(self, fn) -> None:
        """Controller injects `fn(trial_id) -> Trial` so explore can read the
        donor's config and checkpoint."""
        self._trial_reader = fn

    def _sign(self) -> float:
        return 1.0 if (self.mode or "max") == "max" else -1.0

    def _quantiles(self):
        ranked = sorted(self._scores.items(), key=lambda kv: kv[1])
        n = max(1, int(len(ranked) * self.quantile))
        if len(ranked) < 2 * n:
            return [], []
        return [t for t, _ in ranked[:n]], [t for t, _ in ranked[-n:]]

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        """Mutate a copy of `config`. Spec semantics: list = categorical
        choices (perturb moves to a neighboring choice), (lo, hi) tuple =
        continuous range (perturb multiplies by 0.8/1.2, clamped), callable
        = sampler (always resampled when chosen)."""
        out = dict(config)
        for key, spec in self.mutations.items():
            resample = self._rng.random() < self.resample_prob or key not in out
            if callable(spec):
                if resample:
                    out[key] = spec()
                continue
            if isinstance(spec, list):
                if resample:
                    out[key] = self._rng.choice(spec)
                else:
                    try:
                        i = spec.index(out[key])
                        j = max(0, min(len(spec) - 1,
                                       i + self._rng.choice((-1, 1))))
                        out[key] = spec[j]
                    except ValueError:
                        out[key] = self._rng.choice(spec)
                continue
            if isinstance(spec, tuple) and len(spec) == 2:
                lo, hi = spec
                if resample:
                    val = self._rng.uniform(lo, hi)
                else:
                    val = out[key] * self._rng.choice((0.8, 1.2))
                val = max(lo, min(hi, val))
                out[key] = int(round(val)) if isinstance(
                    out.get(key), int
                ) else val
                continue
            raise ValueError(
                f"unsupported mutation spec for {key!r}: {spec!r}"
            )
        return out

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]):
        t = result.get(self.time_attr, 0)
        metric = result.get(self.metric)
        if metric is None:
            return CONTINUE
        self._scores[trial_id] = self._sign() * float(metric)
        last = self._last_perturb.setdefault(trial_id, 0)
        if t - last < self.interval:
            return CONTINUE
        self._last_perturb[trial_id] = t
        bottom, top = self._quantiles()
        if trial_id not in bottom or not top or self._trial_reader is None:
            return CONTINUE
        donor_id = self._rng.choice(top)
        donor = self._trial_reader(donor_id)
        if donor is None or not donor.checkpoint_path:
            return CONTINUE
        self.num_perturbations += 1
        return (EXPLOIT, self._explore(donor.config), donor.checkpoint_path)

    def on_trial_complete(self, trial_id, result) -> None:
        self._scores.pop(trial_id, None)


class HyperBandScheduler(TrialScheduler):
    """Synchronous successive-halving brackets (reference:
    tune/schedulers/hyperband.py HyperBandScheduler).

    Trials are assigned round-robin to `brackets` cohorts; bracket b's first
    milestone is grace_period * eta**b (classic HyperBand trades more trials
    at small budgets against fewer at large ones). At each milestone the
    WHOLE cohort synchronizes: every live trial pauses on arrival, and when
    the last one arrives the top 1/eta continue (resume from checkpoint) and
    the rest stop. Requires checkpointing trainables for pause/resume.
    """

    def __init__(
        self,
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        time_attr: str = "training_iteration",
        max_t: int = 81,
        grace_period: int = 1,
        reduction_factor: float = 3,
        brackets: int = 1,
    ):
        self.metric, self.mode = metric, mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace = grace_period
        self.eta = reduction_factor
        self.n_brackets = max(1, brackets)
        # bracket -> list of milestones
        self.milestones: Dict[int, List[int]] = {}
        for b in range(self.n_brackets):
            ms, t = [], grace_period * reduction_factor**b
            while t < max_t:
                ms.append(int(t))
                t *= reduction_factor
            self.milestones[b] = ms or [int(max_t)]
        self._bracket_of: Dict[str, int] = {}
        self._next_assign = 0
        # (bracket, milestone) -> {trial: signed score}
        self._rung: Dict[tuple, Dict[str, float]] = collections.defaultdict(dict)
        self._rung_idx: Dict[str, int] = {}
        self._live: Dict[int, set] = collections.defaultdict(set)
        self._closed: set = set()  # (bracket, milestone) rungs already halved
        self._actions: List[tuple] = []  # (trial_id, RESUME | STOP)

    def _sign(self) -> float:
        return 1.0 if (self.mode or "max") == "max" else -1.0

    def on_trial_add(self, trial_id: str) -> None:
        self._bracket(trial_id)

    def _bracket(self, trial_id: str) -> int:
        # Membership is normally fixed by on_trial_add before any trial
        # runs; the lazy path only covers schedulers driven outside the
        # controller. Without up-front membership a fast trial could close
        # a rung before slower trials joined the cohort.
        if trial_id not in self._bracket_of:
            b = self._next_assign % self.n_brackets
            self._next_assign += 1
            self._bracket_of[trial_id] = b
            self._live[b].add(trial_id)
        return self._bracket_of[trial_id]

    def _maybe_close_rung(self, b: int, milestone: int) -> None:
        if (b, milestone) in self._closed:
            return  # already halved; a late recheck must not re-emit actions
        rung = self._rung[(b, milestone)]
        live = self._live[b]
        if not live or not (set(rung) >= live):
            return  # cohort not complete yet
        self._closed.add((b, milestone))
        # Rank only members still alive (dead ones cannot resume).
        alive = {tid: v for tid, v in rung.items() if tid in live}
        # Halve over trials that can actually resume: when cohort members
        # died after reporting, keep_n from len(rung) would resume more than
        # 1/eta of the survivors and weaken the selection.
        keep_n = max(1, int(len(alive) / self.eta))
        ranked = sorted(alive.items(), key=lambda kv: -kv[1])
        for i, (tid, _) in enumerate(ranked):
            if i < keep_n:
                self._actions.append((tid, RESUME))
            else:
                self._live[b].discard(tid)
                self._actions.append((tid, STOP))

    def _discard_live(self, trial_id: str) -> None:
        """Remove a trial from its cohort and recheck rungs its departure may
        have completed (a dead/finished member must not block the barrier)."""
        b = self._bracket_of.get(trial_id)
        if b is None or trial_id not in self._live[b]:
            return
        self._live[b].discard(trial_id)
        for m in self.milestones[b]:
            if (b, m) in self._rung:
                self._maybe_close_rung(b, m)

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]):
        t = result.get(self.time_attr, 0)
        metric = result.get(self.metric)
        if metric is None:
            return CONTINUE
        b = self._bracket(trial_id)
        if t >= self.max_t:
            # Full budget used: normal completion, not a halving kill.
            self._discard_live(trial_id)
            return COMPLETE
        ms = self.milestones[b]
        idx = self._rung_idx.setdefault(trial_id, 0)
        if idx >= len(ms) or t < ms[idx]:
            return CONTINUE
        milestone = ms[idx]
        self._rung[(b, milestone)][trial_id] = self._sign() * float(metric)
        self._rung_idx[trial_id] = idx + 1
        self._maybe_close_rung(b, milestone)
        return PAUSE

    def on_trial_complete(self, trial_id, result) -> None:
        self._discard_live(trial_id)

    def pop_actions(self) -> List[tuple]:
        """Controller drains (trial_id, RESUME|STOP) decisions produced when
        a rung cohort completed."""
        out, self._actions = self._actions, []
        return out
