"""Class-based Trainable API (reference:
python/ray/tune/trainable/trainable.py Trainable).

Subclass and override setup/step/save_checkpoint/load_checkpoint; the Tuner
wraps the class into a checkpointing trial loop. Because every step reports
with a checkpoint, class Trainables compose with PBT (exploit = checkpoint
restore + config swap) and synchronous HyperBand (pause/resume) for free.

    class MyTrainable(tune.Trainable):
        def setup(self, config):
            self.lr = config["lr"]; self.acc = 0.0
        def step(self):
            self.acc += self.lr
            return {"acc": self.acc}
        def save_checkpoint(self, d):
            json.dump({"acc": self.acc}, open(os.path.join(d, "s.json"), "w"))
        def load_checkpoint(self, d):
            self.acc = json.load(open(os.path.join(d, "s.json")))["acc"]

    Tuner(MyTrainable, param_space={"lr": tune.uniform(0, 1)},
          run_config=RunConfig(stop={"training_iteration": 20})).fit()

Stopping: a trial ends when step() returns {"done": True}, or when a
RunConfig.stop criterion is met (enforced by the controller).
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, Optional


class Trainable:
    """Override setup/step (+ save_checkpoint/load_checkpoint for resume,
    PBT, and HyperBand support)."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        self.config = dict(config or {})
        self.iteration = 0
        self.setup(self.config)

    # -- user hooks ----------------------------------------------------------

    def setup(self, config: Dict[str, Any]) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError("Trainable subclasses must implement step()")

    def save_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def cleanup(self) -> None:
        pass

    # -- reference-compat alias ----------------------------------------------

    def train(self) -> Dict[str, Any]:
        """One training iteration (reference: Trainable.train wraps step)."""
        result = self.step()
        self.iteration += 1
        return result


_META = ".trainable_meta.json"


def class_trainable_to_fn(cls):
    """Wrap a Trainable subclass into the function-trainable loop the
    controller runs: instantiate, restore from the session checkpoint (PBT
    exploit / HyperBand resume / Tuner.restore), then step-report-checkpoint
    until stopped."""

    def _loop(config):
        from ray_tpu import tune
        from ray_tpu.train._checkpoint import Checkpoint

        t = cls(config)
        ckpt = tune.get_checkpoint()
        if ckpt is not None:
            with ckpt.as_directory() as d:
                meta = os.path.join(d, _META)
                if os.path.exists(meta):
                    t.iteration = json.load(open(meta))["iteration"]
                t.load_checkpoint(d)
        while True:
            result = t.train()
            with tempfile.TemporaryDirectory() as d:
                t.save_checkpoint(d)
                json.dump(
                    {"iteration": t.iteration}, open(os.path.join(d, _META), "w")
                )
                result.setdefault("training_iteration", t.iteration)
                tune.report(result, checkpoint=Checkpoint.from_directory(d))
            if result.get("done"):
                break
        t.cleanup()

    _loop.__name__ = getattr(cls, "__name__", "trainable")
    _loop._tune_resources = getattr(cls, "_tune_resources", None)
    return _loop
