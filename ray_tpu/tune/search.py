"""Search spaces + variant generation (reference: python/ray/tune/search/
sample.py + basic_variant.py BasicVariantGenerator).

Grid axes cross-product; sampled domains draw `num_samples` times; each grid
cross-product is repeated per sample (reference semantics: num_samples
multiplies the grid).
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, List, Optional, Tuple


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math

        if low <= 0 or high <= 0:
            raise ValueError("loguniform bounds must be positive")
        self._lo, self._hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self._lo, self._hi))


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Function(Domain):
    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn

    def sample(self, rng):
        return self.fn()


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


# -- public constructors (tune.uniform etc.) ---------------------------------


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def choice(categories) -> Choice:
    return Choice(categories)


def sample_from(fn: Callable[[], Any]) -> Function:
    return Function(fn)


def grid_search(values) -> Dict[str, Any]:
    return {"grid_search": list(values)}


def _walk(space: Any, path: Tuple) -> Tuple[List[Tuple[Tuple, GridSearch]], List[Tuple[Tuple, Domain]]]:
    """Collect (path, GridSearch) and (path, Domain) leaves from a nested
    dict param space."""
    grids: List[Tuple[Tuple, GridSearch]] = []
    domains: List[Tuple[Tuple, Domain]] = []
    if isinstance(space, dict):
        if set(space.keys()) == {"grid_search"}:
            grids.append((path, GridSearch(space["grid_search"])))
            return grids, domains
        for k, v in space.items():
            g, d = _walk(v, path + (k,))
            grids.extend(g)
            domains.extend(d)
    elif isinstance(space, GridSearch):
        grids.append((path, space))
    elif isinstance(space, Domain):
        domains.append((path, space))
    return grids, domains


def _set_path(cfg: Dict, path: Tuple, value: Any) -> None:
    d = cfg
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def _materialize(space: Any) -> Dict:
    """Deep-copy the static parts of the space into a plain config dict."""
    if isinstance(space, dict):
        if set(space.keys()) == {"grid_search"}:
            return {}
        return {
            k: _materialize(v) if isinstance(v, dict) else v
            for k, v in space.items()
            if not isinstance(v, (Domain, GridSearch))
            and not (isinstance(v, dict) and set(v.keys()) == {"grid_search"})
        }
    return {}


class BasicVariantGenerator:
    """Grid cross-product × num_samples random draws."""

    def __init__(self, seed: Optional[int] = None):
        self._rng = random.Random(seed)

    def generate(self, param_space: Dict, num_samples: int) -> List[Dict]:
        grids, domains = _walk(param_space, ())
        grid_axes = [
            [(path, v) for v in g.values] for path, g in grids
        ] or [[]]
        configs: List[Dict] = []
        for _ in range(num_samples):
            for combo in itertools.product(*grid_axes) if grids else [()]:
                cfg = _materialize(param_space)
                for path, value in combo:
                    _set_path(cfg, path, value)
                for path, dom in domains:
                    _set_path(cfg, path, dom.sample(self._rng))
                configs.append(cfg)
        return configs
