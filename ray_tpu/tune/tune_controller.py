"""Trial lifecycle + the controller event loop (reference:
python/ray/tune/execution/tune_controller.py:68).

Each trial runs inside a TrainWorker actor (shared machinery with train:
world-size-1 session, report queue drained by poll). The controller starts
trials as concurrency slots free up, drains results, feeds the scheduler, and
enforces STOP decisions by killing the trial actor.
"""

from __future__ import annotations

import os
import pickle
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.train._session import TrialInfo
from ray_tpu.tune import schedulers as sched_mod

PENDING, RUNNING, PAUSED, TERMINATED, ERROR = (
    "PENDING", "RUNNING", "PAUSED", "TERMINATED", "ERROR",
)


@dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    status: str = PENDING
    history: List[Dict[str, Any]] = field(default_factory=list)
    checkpoint_path: Optional[str] = None
    error: Optional[str] = None
    early_stopped: bool = False
    num_perturbations: int = 0  # PBT exploit/explore restarts
    actor: Any = None
    run_ref: Any = None

    @property
    def last_result(self) -> Optional[Dict[str, Any]]:
        return self.history[-1] if self.history else None

    def public_state(self) -> Dict[str, Any]:
        return {
            "trial_id": self.trial_id,
            "config": self.config,
            "status": self.status,
            "history": self.history,
            "checkpoint_path": self.checkpoint_path,
            "error": self.error,
            "early_stopped": self.early_stopped,
        }


class TuneController:
    def __init__(
        self,
        trainable: Callable,
        trials: List[Trial],
        *,
        experiment_name: str,
        experiment_dir: str,
        storage_path: str,
        scheduler: Optional[sched_mod.TrialScheduler] = None,
        max_concurrent: Optional[int] = None,
        resources_per_trial: Optional[Dict[str, float]] = None,
        poll_timeout: float = 2.0,
        searcher: Optional[Any] = None,
        num_samples: int = 0,
    ):
        self.trainable_blob = cloudpickle.dumps(trainable)
        self.trials = trials
        # Sequential suggest/observe searcher (reference: tune/search/
        # searcher.py protocol): trials are created on demand via
        # searcher.suggest() as slots free up, and completions feed back
        # through searcher.on_trial_complete so the model adapts.
        self.searcher = searcher
        self.num_samples = num_samples
        self.experiment_name = experiment_name
        self.experiment_dir = experiment_dir
        self.storage_path = storage_path
        self.scheduler = scheduler or sched_mod.FIFOScheduler()
        self.max_concurrent = max_concurrent or len(trials) or 1
        self.resources = resources_per_trial or {"CPU": 1.0}
        self.poll_timeout = poll_timeout
        self.stop_criteria: Optional[Dict[str, Any]] = None
        # PBT's explore step reads donor configs/checkpoints through this.
        by_id = {t.trial_id: t for t in trials}
        if hasattr(self.scheduler, "set_trial_state_reader"):
            self.scheduler.set_trial_state_reader(by_id.get)
        for t in trials:
            self.scheduler.on_trial_add(t.trial_id)

    # -- trial actor management ---------------------------------------------

    def _start_trial(self, trial: Trial) -> None:
        from ray_tpu.train._worker_group import TrainWorker

        cls = ray_tpu.remote(TrainWorker)
        opts: Dict[str, Any] = {"max_concurrency": 4}
        res = dict(self.resources)
        opts["num_cpus"] = res.pop("CPU", 1.0)
        if "TPU" in res:
            opts["num_tpus"] = res.pop("TPU")
        if res:
            opts["resources"] = res
        trial.actor = cls.options(**opts).remote(None)
        trial_dir = os.path.join(self.experiment_dir, trial.trial_id)
        ray_tpu.get(
            trial.actor.setup_session.remote(
                world_rank=0,
                world_size=1,
                local_rank=0,
                local_world_size=1,
                node_rank=0,
                trial_info=TrialInfo(
                    name=trial.trial_id,
                    experiment_name=self.experiment_name,
                    trial_id=trial.trial_id,
                    storage_path=self.storage_path,
                    trial_dir=trial_dir,
                ),
                latest_checkpoint_path=trial.checkpoint_path,
                dataset_shards={},
                loop_config=trial.config,
                collective_group=None,
                # Resumed/exploited trials continue the checkpoint-dir
                # numbering where their history left off, so post-resume
                # checkpoints never overwrite pre-pause directories.
                start_iteration=len(trial.history),
            )
        )
        trial.run_ref = trial.actor.run.remote(self.trainable_blob)
        trial.status = RUNNING

    def _stop_trial(self, trial: Trial, status: str, error: Optional[str] = None):
        trial.status = status
        trial.error = error
        if trial.actor is not None:
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
        if self.searcher is not None and status in (TERMINATED, ERROR):
            self.searcher.on_trial_complete(
                trial.trial_id, trial.last_result if status == TERMINATED else None
            )

    def _suggest_trials(self) -> None:
        """Top up pending trials from the searcher while sample budget and
        concurrency allow."""
        if self.searcher is None:
            return
        live = [t for t in self.trials if t.status in (RUNNING, PENDING, PAUSED)]
        while (
            len(self.trials) < self.num_samples
            and len(live) < self.max_concurrent
        ):
            tid = new_trial_id()
            config = self.searcher.suggest(tid)
            if config is None:
                break
            trial = Trial(trial_id=tid, config=config)
            self.trials.append(trial)
            self.scheduler.on_trial_add(tid)
            live.append(trial)

    # -- the loop ------------------------------------------------------------

    def _apply_decision(self, trial: Trial, decision) -> bool:
        """Enforce a scheduler decision; True if the trial stopped running."""
        if isinstance(decision, tuple) and decision[0] == sched_mod.EXPLOIT:
            # PBT exploit/explore: adopt the donor's checkpoint + a mutated
            # config and restart the trial in place (history continues).
            _, new_config, donor_ckpt = decision
            trial.config = dict(new_config)
            trial.checkpoint_path = donor_ckpt
            trial.num_perturbations += 1
            self._stop_trial(trial, PENDING)
            return True
        if decision == sched_mod.PAUSE:
            # The trial resumes from its latest reported checkpoint when the
            # scheduler releases it (synchronous rung barrier).
            self._stop_trial(trial, PAUSED)
            return True
        if decision in (sched_mod.STOP, sched_mod.COMPLETE):
            # COMPLETE = budget exhausted (normal end); STOP = killed by the
            # scheduler's selection — only the latter is "early stopped".
            trial.early_stopped = decision == sched_mod.STOP
            self._stop_trial(trial, TERMINATED)
            self.scheduler.on_trial_complete(trial.trial_id, trial.last_result)
            return True
        return False

    def _drain_scheduler_actions(self) -> None:
        if not hasattr(self.scheduler, "pop_actions"):
            return
        by_id = {t.trial_id: t for t in self.trials}
        for trial_id, action in self.scheduler.pop_actions():
            trial = by_id.get(trial_id)
            if trial is None or trial.status in (TERMINATED, ERROR):
                continue
            if action == sched_mod.RESUME:
                if trial.status == PAUSED:
                    trial.status = PENDING
            elif action == sched_mod.STOP:
                trial.early_stopped = True
                self._stop_trial(trial, TERMINATED)
                self.scheduler.on_trial_complete(
                    trial.trial_id, trial.last_result
                )

    def _hit_stop_criteria(self, metrics: Dict[str, Any]) -> bool:
        if not self.stop_criteria:
            return False
        for key, threshold in self.stop_criteria.items():
            val = metrics.get(key)
            if val is None:
                continue
            try:
                if float(val) >= float(threshold):
                    return True
            except (TypeError, ValueError):
                # Non-numeric reported value (e.g. a status string) must not
                # abort the whole experiment from inside the poll loop.
                continue
        return False

    def run(self, result_cb: Optional[Callable[[Trial, Dict], None]] = None):
        while True:
            self._drain_scheduler_actions()
            self._suggest_trials()
            running = [t for t in self.trials if t.status == RUNNING]
            pending = [t for t in self.trials if t.status == PENDING]
            paused = [t for t in self.trials if t.status == PAUSED]
            if not running and not pending:
                if not paused:
                    break
                # Every live trial is paused and the scheduler produced no
                # actions: a dead cohort member can cause this. Resuming
                # everyone beats deadlocking the experiment.
                for t in paused:
                    t.status = PENDING
                continue
            # Fill free slots.
            for t in pending[: max(0, self.max_concurrent - len(running))]:
                self._start_trial(t)
                running.append(t)
            # Drain one poll round across all running trials (each poll
            # batch-drains the trial's whole result queue).
            refs = [
                t.actor.poll.remote(self.poll_timeout, None) for t in running
            ]
            for trial, rep in zip(running, self._safe_get(refs, running)):
                if rep is None:  # actor died
                    self._stop_trial(trial, ERROR, "trial actor died")
                    self.scheduler.on_trial_complete(trial.trial_id, None)
                    continue
                if "results" in rep:
                    for r in rep["results"]:
                        metrics = dict(r["metrics"])
                        metrics.setdefault(
                            "training_iteration", r["iteration"] + 1
                        )
                        metrics.setdefault("trial_id", trial.trial_id)
                        trial.history.append(metrics)
                        if r["checkpoint_path"]:
                            trial.checkpoint_path = r["checkpoint_path"]
                        if result_cb:
                            result_cb(trial, metrics)
                        if self._hit_stop_criteria(metrics):
                            self._stop_trial(trial, TERMINATED)
                            self.scheduler.on_trial_complete(
                                trial.trial_id, trial.last_result
                            )
                            break
                        decision = self.scheduler.on_trial_result(
                            trial.trial_id, metrics
                        )
                        if self._apply_decision(trial, decision):
                            break
                elif rep.get("done"):
                    if rep.get("error"):
                        self._stop_trial(trial, ERROR, rep["error"])
                    else:
                        self._stop_trial(trial, TERMINATED)
                    self.scheduler.on_trial_complete(
                        trial.trial_id, trial.last_result
                    )
            self.save_state()

    def _safe_get(self, refs, trials):
        out = []
        for ref, trial in zip(refs, trials):
            try:
                out.append(ray_tpu.get(ref, timeout=self.poll_timeout + 60))
            except Exception:
                out.append(None)
        return out

    # -- persistence (Tuner.restore) ----------------------------------------

    def save_state(self) -> None:
        state = {
            "experiment_name": self.experiment_name,
            "metric": getattr(self, "metric", None),
            "mode": getattr(self, "mode", None),
            "trials": [t.public_state() for t in self.trials],
        }
        os.makedirs(self.experiment_dir, exist_ok=True)
        tmp = os.path.join(self.experiment_dir, ".experiment_state.tmp")
        with open(tmp, "wb") as f:
            pickle.dump(state, f)
        os.replace(tmp, os.path.join(self.experiment_dir, "experiment_state.pkl"))

    @staticmethod
    def load_state(experiment_dir: str) -> Dict[str, Any]:
        with open(os.path.join(experiment_dir, "experiment_state.pkl"), "rb") as f:
            return pickle.load(f)


def new_trial_id() -> str:
    return uuid.uuid4().hex[:8]
