"""Adaptive search algorithms (reference: python/ray/tune/search/ — the
Searcher interface of searcher.py plus the optuna/hyperopt-style adapters).

External bayesopt libraries aren't available in this environment, so the
TPE searcher is implemented natively: the tree-structured Parzen estimator
of Bergstra et al. (the algorithm behind hyperopt/optuna defaults) over the
same Domain leaves tune's random search uses. Sequential protocol:
``suggest(trial_id) -> config`` draws a candidate, ``on_trial_complete``
feeds the observed metric back; after ``n_initial`` random startup trials,
candidates are drawn from a kernel-density model of the GOOD observations
and ranked by the good/bad density ratio.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.search import (
    Choice,
    Domain,
    GridSearch,
    LogUniform,
    RandInt,
    Uniform,
    _materialize,
    _set_path,
    _walk,
)


class Searcher:
    """Sequential suggest/observe interface (reference: search/searcher.py)."""

    metric: Optional[str] = None
    mode: Optional[str] = None

    def set_metric(self, metric: Optional[str], mode: Optional[str]) -> None:
        if self.metric is None:
            self.metric = metric
        if self.mode is None:
            self.mode = mode

    def set_search_space(self, param_space: Dict) -> None:
        raise NotImplementedError

    def suggest(self, trial_id: str) -> Optional[Dict]:
        raise NotImplementedError

    def on_trial_complete(
        self, trial_id: str, result: Optional[Dict[str, Any]]
    ) -> None:
        pass


class TPESearcher(Searcher):
    """Native tree-structured Parzen estimator.

    Per dimension, observations are split at the gamma-quantile of the
    objective into good/bad sets; `n_candidates` draws from the good set's
    Parzen mixture are ranked by l(x)/g(x) and the best wins. Continuous
    dims use Gaussian kernels (log-space for LogUniform); Choice/RandInt use
    smoothed categorical counts. Grid-search leaves are not supported — use
    the grid generator for those.
    """

    def __init__(
        self,
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        n_initial: int = 8,
        gamma: float = 0.25,
        n_candidates: int = 24,
        seed: Optional[int] = None,
    ):
        self.metric, self.mode = metric, mode
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._dims: List[Tuple[Tuple, Domain]] = []
        self._space: Optional[Dict] = None
        # trial_id -> {path: value}; observations: (values dict, score)
        self._pending: Dict[str, Dict[Tuple, Any]] = {}
        self._obs: List[Tuple[Dict[Tuple, Any], float]] = []

    # -- space ---------------------------------------------------------------

    def set_search_space(self, param_space: Dict) -> None:
        grids, domains = _walk(param_space, ())
        if grids:
            raise ValueError(
                "TPESearcher does not accept grid_search leaves; use plain "
                "domains (tune.uniform/loguniform/randint/choice)"
            )
        if not domains:
            raise ValueError("param_space has no tunable domains")
        self._space = param_space
        self._dims = domains

    # -- model ---------------------------------------------------------------

    def _split(self):
        sign = 1.0 if (self.mode or "max") == "max" else -1.0
        ranked = sorted(self._obs, key=lambda o: -sign * o[1])
        n_good = max(1, int(math.ceil(len(ranked) * self.gamma)))
        return ranked[:n_good], ranked[n_good:]

    @staticmethod
    def _to_internal(dom: Domain, v: float):
        return math.log(v) if isinstance(dom, LogUniform) else float(v)

    @staticmethod
    def _from_internal(dom: Domain, x: float):
        return math.exp(x) if isinstance(dom, LogUniform) else x

    def _bounds(self, dom: Domain) -> Tuple[float, float]:
        if isinstance(dom, Uniform):
            return dom.low, dom.high
        if isinstance(dom, LogUniform):
            return dom._lo, dom._hi
        if isinstance(dom, RandInt):
            return float(dom.low), float(dom.high - 1)
        raise TypeError(dom)

    def _parzen_sample(self, dom, points: List[float], rng) -> float:
        lo, hi = self._bounds(dom)
        width = (hi - lo) or 1.0
        sigma = max(width / max(len(points), 1), width / 25.0)
        center = rng.choice(points) if points else rng.uniform(lo, hi)
        return min(hi, max(lo, rng.gauss(center, sigma)))

    def _parzen_logpdf(self, dom, points: List[float], x: float) -> float:
        lo, hi = self._bounds(dom)
        width = (hi - lo) or 1.0
        sigma = max(width / max(len(points), 1), width / 25.0)
        if not points:
            return -math.log(width)
        acc = 0.0
        for c in points:
            acc += math.exp(-0.5 * ((x - c) / sigma) ** 2)
        return math.log(acc / (len(points) * sigma * math.sqrt(2 * math.pi)) + 1e-300)

    def _suggest_dim(self, path: Tuple, dom: Domain, good, bad):
        if isinstance(dom, (Choice, RandInt)) and isinstance(dom, Choice):
            cats = dom.categories
            # Smoothed categorical TPE: P(cat|good) / P(cat|bad).
            def counts(obs):
                c = {i: 1.0 for i in range(len(cats))}
                for values, _ in obs:
                    v = values.get(path)
                    for i, cat in enumerate(cats):
                        if cat == v:
                            c[i] += 1.0
                total = sum(c.values())
                return {i: n / total for i, n in c.items()}

            pg, pb = counts(good), counts(bad)
            best = max(
                range(len(cats)),
                key=lambda i: pg[i] / pb[i] + self._rng.random() * 1e-6,
            )
            return cats[best]
        good_pts = [
            self._to_internal(dom, v[path]) for v, _ in good if path in v
        ]
        bad_pts = [
            self._to_internal(dom, v[path]) for v, _ in bad if path in v
        ]
        best_x, best_score = None, -math.inf
        for _ in range(self.n_candidates):
            x = self._parzen_sample(dom, good_pts, self._rng)
            score = self._parzen_logpdf(dom, good_pts, x) - self._parzen_logpdf(
                dom, bad_pts, x
            )
            if score > best_score:
                best_x, best_score = x, score
        val = self._from_internal(dom, best_x)
        if isinstance(dom, RandInt):
            val = int(round(val))
            val = min(dom.high - 1, max(dom.low, val))
        return val

    # -- protocol ------------------------------------------------------------

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if self._space is None:
            raise RuntimeError("set_search_space() was not called")
        values: Dict[Tuple, Any] = {}
        startup = len(self._obs) < self.n_initial
        good, bad = (None, None) if startup else self._split()
        for path, dom in self._dims:
            if startup or not bad:
                values[path] = dom.sample(self._rng)
            else:
                values[path] = self._suggest_dim(path, dom, good, bad)
        self._pending[trial_id] = values
        cfg = _materialize(self._space)
        for path, v in values.items():
            _set_path(cfg, path, v)
        return cfg

    def on_trial_complete(self, trial_id, result) -> None:
        values = self._pending.pop(trial_id, None)
        if values is None or result is None:
            return
        metric = result.get(self.metric) if self.metric else None
        if metric is None:
            return
        self._obs.append((values, float(metric)))
