"""Test helpers: force JAX onto a virtual multi-device CPU mesh.

TPU CI runs with one real chip (or none); sharding logic is validated on an
N-device CPU mesh via --xla_force_host_platform_device_count. The TPU plugin
in this image registers itself from sitecustomize and overrides JAX_PLATFORMS,
so CPU forcing needs both the env knob (for fresh worker processes, where an
empty PALLAS_AXON_POOL_IPS skips plugin registration) and a config update (for
an already-running process).
"""

from __future__ import annotations

import os
from typing import Dict


def cpu_mesh_worker_env(num_devices: int = 8) -> Dict[str, str]:
    """Env for spawned worker processes so jax inside them sees N CPU devices."""
    return {
        "PALLAS_AXON_POOL_IPS": "",  # falsy -> TPU plugin registration skipped
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={num_devices}",
        # The force flag is ignored when jax.distributed initializes the
        # multi-process CPU client; this knob covers that path too.
        "JAX_NUM_CPU_DEVICES": str(num_devices),
    }


def force_cpu_mesh(num_devices: int = 8) -> None:
    """Force the CURRENT process's jax onto N virtual CPU devices.

    Must run before first backend use (first jit/device access).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    want = f"--xla_force_host_platform_device_count={num_devices}"
    kept = [
        f
        for f in flags.split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    os.environ["XLA_FLAGS"] = " ".join(kept + [want])
    import jax

    jax.config.update("jax_platforms", "cpu")
