"""PPO: clipped-surrogate policy optimization.

Analog of rllib/algorithms/ppo/ (ppo.py, ppo_learner.py, torch loss at
ppo_torch_learner.py): sync sampling from the env-runner gang, GAE
postprocessing, minibatched multi-epoch SGD on one jitted loss — policy
clip + value clip + entropy bonus.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, gae_advantages
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import RLModuleSpec, forward_pi_vf, init_pi_vf


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=PPO)
        self.lr = 3e-4
        self.train_batch_size = 2048
        self.minibatch_size = 128
        self.num_epochs = 8
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.kl_target = 0.02  # accepted for parity; adaptive KL not applied
        self.grad_clip = 0.5


class PPOLearner(Learner):
    def __init__(self, spec: RLModuleSpec, cfg: Dict[str, Any], **kw):
        self.cfg = cfg
        super().__init__(spec, **kw)

    def init_params(self, rng):
        return init_pi_vf(rng, self.spec)

    def loss_fn(self, params, batch):
        import jax
        import jax.numpy as jnp

        c = self.cfg
        logits, values = forward_pi_vf(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(logp_all, batch["actions"][:, None], axis=-1)[:, 0]
        # Optional row mask (multi-agent: padded rows of individually-
        # terminated agents must not produce gradients).
        mask = batch.get("mask")
        if mask is None:
            wmean = jnp.mean
        else:
            denom = jnp.maximum(mask.sum(), 1.0)

            def wmean(x):
                return (x * mask).sum() / denom

        ratio = jnp.exp(logp - batch["logp_old"])
        adv = batch["advantages"]
        surr1 = ratio * adv
        surr2 = jnp.clip(ratio, 1 - c["clip_param"], 1 + c["clip_param"]) * adv
        policy_loss = -wmean(jnp.minimum(surr1, surr2))

        vf_err = values - batch["value_targets"]
        vf_clipped = batch["values_old"] + jnp.clip(
            values - batch["values_old"], -c["vf_clip_param"], c["vf_clip_param"]
        )
        vf_err_clipped = vf_clipped - batch["value_targets"]
        vf_loss = 0.5 * wmean(
            jnp.maximum(jnp.square(vf_err), jnp.square(vf_err_clipped))
        )

        probs = jax.nn.softmax(logits)
        entropy = -wmean(jnp.sum(probs * logp_all, axis=-1))
        kl = wmean(batch["logp_old"] - logp)

        loss = (
            policy_loss
            + c["vf_loss_coeff"] * vf_loss
            - c["entropy_coeff"] * entropy
        )
        return loss, {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "kl": kl,
        }


class PPO(Algorithm):
    policy_kind = "pi_vf"
    supports_multi_agent = True

    def _learner_builder(self, obs_dim: int, num_actions: int) -> Callable[[], Any]:
        cfg = self.config
        spec = RLModuleSpec(
            obs_dim=obs_dim,
            num_actions=num_actions,
            hidden=tuple(cfg.model.get("hidden", (64, 64))),
            vf_share_layers=bool(cfg.model.get("vf_share_layers", False)),
        )
        loss_cfg = {
            "clip_param": cfg.clip_param,
            "vf_clip_param": cfg.vf_clip_param,
            "vf_loss_coeff": cfg.vf_loss_coeff,
            "entropy_coeff": cfg.entropy_coeff,
        }
        lr, grad_clip, seed = cfg.lr, cfg.grad_clip, cfg.seed

        def build():
            return PPOLearner(spec, loss_cfg, lr=lr, grad_clip=grad_clip, seed=seed)

        return build

    def _flatten_with_gae(self, policy_batches, obs_dim: int) -> Dict[str, np.ndarray]:
        """GAE per runner batch, then flatten to one train batch."""
        cfg = self.config
        has_mask = any("mask" in b for b in policy_batches)
        keys = [
            "obs", "actions", "logp_old", "advantages",
            "value_targets", "values_old",
        ] + (["mask"] if has_mask else [])
        flat: Dict[str, list] = {k: [] for k in keys}
        for b in policy_batches:
            adv, ret = gae_advantages(
                b["rewards"],
                b["values"],
                b["terminateds"],
                b["truncateds"],
                b["bootstrap_value"],
                cfg.gamma,
                cfg.lambda_,
                boundary_values=b.get("boundary_values"),
            )
            flat["obs"].append(b["obs"].reshape(-1, obs_dim))
            flat["actions"].append(b["actions"].reshape(-1))
            flat["logp_old"].append(b["logp"].reshape(-1))
            flat["advantages"].append(adv.reshape(-1))
            flat["value_targets"].append(ret.reshape(-1))
            flat["values_old"].append(b["values"].reshape(-1))
            if has_mask:
                flat["mask"].append(
                    b.get(
                        "mask", np.ones_like(b["values"], np.float32)
                    ).reshape(-1)
                )
        train_batch = {k: np.concatenate(v) for k, v in flat.items()}
        adv = train_batch["advantages"]
        if has_mask:
            # Masked normalization: padded rows must not skew the stats.
            m = train_batch["mask"]
            n = max(float(m.sum()), 1.0)
            mean = float((adv * m).sum() / n)
            var = float(((adv - mean) ** 2 * m).sum() / n)
            train_batch["advantages"] = (adv - mean) / (var**0.5 + 1e-8)
        else:
            train_batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
        return train_batch

    def _sgd_epochs(self, train_batch, learner_group, rng) -> Dict[str, float]:
        """Minibatched multi-epoch SGD on one learner group."""
        cfg = self.config
        size = len(train_batch["obs"])
        mb = min(cfg.minibatch_size, size)
        last_metrics: Dict[str, float] = {}
        for _ in range(cfg.num_epochs):
            perm = rng.permutation(size)
            for start in range(0, size - mb + 1, mb):
                idx = perm[start : start + mb]
                minibatch = {k: v[idx] for k, v in train_batch.items()}
                last_metrics = learner_group.update_from_batch(minibatch)
        return last_metrics

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        n_runners = max(1, cfg.num_env_runners)
        # Both runner kinds vectorize num_envs_per_env_runner (multi-agent
        # runners step num_envs env copies per lockstep step), so the
        # per-runner step count divides by it in both cases — the train
        # batch stays at train_batch_size env steps.
        envs_per_runner = cfg.num_envs_per_env_runner
        steps_per_runner = max(
            1, cfg.train_batch_size // (n_runners * envs_per_runner)
        )
        batches = self.env_runner_group.sample(steps_per_runner)
        self._env_steps_total += sum(b["env_steps"] for b in batches)
        rng = np.random.RandomState(cfg.seed + self.iteration)

        if self.multi_agent:
            # Per-policy update: each policy gets its own GAE + SGD epochs
            # on its own learner group (reference: one Learner.update over a
            # MultiRLModule; here independent jit programs per policy).
            metrics: Dict[str, Any] = {}
            for pid, lg in self.learner_groups.items():
                pbatches = [
                    b["policies"][pid] for b in batches if pid in b["policies"]
                ]
                if not pbatches:
                    continue
                obs_dim = self.policy_spaces[pid][0]
                train_batch = self._flatten_with_gae(pbatches, obs_dim)
                for k, v in self._sgd_epochs(train_batch, lg, rng).items():
                    metrics[f"{pid}/{k}"] = v
            self._sync_weights()
            return {**self._episode_metrics(batches), **metrics}

        train_batch = self._flatten_with_gae(batches, self.obs_dim)
        last_metrics = self._sgd_epochs(train_batch, self.learner_group, rng)
        self._sync_weights()
        return {**self._episode_metrics(batches), **last_metrics}
