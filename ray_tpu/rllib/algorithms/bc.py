"""BC: behavior cloning from offline data.

Analog of rllib/algorithms/bc/ (bc.py + the offline-data pipeline,
offline/offline_data.py): supervised imitation of logged (obs, action)
transitions from a ray_tpu.data Dataset — no environment interaction during
training (the env is only used for action/observation spaces and optional
evaluation rollouts).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import RLModuleSpec, forward_pi_vf, init_pi_vf
from ray_tpu.rllib.utils.offline import materialize_offline, validate_discrete_actions


class BCConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=BC)
        self.lr = 1e-3
        self.train_batch_size = 256
        self.updates_per_iteration = 32


class BCLearner(Learner):
    def init_params(self, rng):
        return init_pi_vf(rng, self.spec)

    def loss_fn(self, params, batch):
        import jax
        import jax.numpy as jnp

        logits, _ = forward_pi_vf(params, batch["obs"])
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, batch["actions"][:, None], axis=-1)[:, 0]
        loss = jnp.mean(nll)
        acc = jnp.mean(
            (jnp.argmax(logits, axis=-1) == batch["actions"]).astype(jnp.float32)
        )
        return loss, {"bc_loss": loss, "action_accuracy": acc}


class BC(Algorithm):
    policy_kind = "pi_vf"

    def __init__(self, config: AlgorithmConfig):
        if config.offline_input is None:
            raise ValueError(
                "BC requires offline data: config.offline_data(input_=dataset)"
            )
        super().__init__(config)
        self._rows = materialize_offline(config.offline_input)
        self._obs = np.asarray(
            [r["obs"] for r in self._rows], dtype=np.float32
        ).reshape(len(self._rows), -1)
        self._acts = validate_discrete_actions(
            np.asarray([r["actions"] for r in self._rows]),
            self.num_actions,
            "BC",
        )
        self._rng = np.random.RandomState(config.seed)

    def _learner_builder(self, obs_dim: int, num_actions: int) -> Callable[[], Any]:
        cfg = self.config
        spec = RLModuleSpec(
            obs_dim=obs_dim,
            num_actions=num_actions,
            hidden=tuple(cfg.model.get("hidden", (64, 64))),
        )
        lr, grad_clip, seed = cfg.lr, cfg.grad_clip, cfg.seed

        def build():
            return BCLearner(spec, lr=lr, grad_clip=grad_clip, seed=seed)

        return build

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        metrics: Dict[str, float] = {}
        for _ in range(cfg.updates_per_iteration):
            idx = self._rng.randint(0, len(self._obs), size=cfg.train_batch_size)
            # Public group API: a plain supervised batch shards across
            # remote learners (grad averaging) or runs locally.
            metrics = self.learner_group.update_from_batch(
                {"obs": self._obs[idx], "actions": self._acts[idx]}
            )
        self._sync_weights()
        return {
            **{k: float(v) for k, v in metrics.items()},
            "num_offline_rows": len(self._rows),
        }

    def evaluate(self, num_steps: int = 256) -> Dict[str, Any]:
        """Greedy evaluation rollout against the configured env."""
        batches = self.env_runner_group.sample(num_steps)
        return self._episode_metrics(batches)
