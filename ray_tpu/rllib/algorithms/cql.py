"""CQL: conservative Q-learning from offline data (discrete CQL(H)).

Analog of rllib/algorithms/cql/ (cql.py + cql_learner): standard double-DQN
TD learning on logged transitions plus the conservative regularizer
alpha * (logsumexp_a Q(s, a) - Q(s, a_logged)), which pushes down
out-of-distribution action values so the greedy policy stays inside the
dataset's support — the failure mode of running plain DQN on a fixed
offline buffer. No environment interaction during training; the env only
provides spaces and optional evaluation rollouts.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.utils.offline import materialize_offline, validate_discrete_actions
from ray_tpu.rllib.algorithms.dqn import DQNLearner


class CQLConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=CQL)
        self.lr = 5e-4
        self.train_batch_size = 64
        self.updates_per_iteration = 64
        self.target_network_update_freq_updates = 50  # learner updates
        self.double_q = True
        self.cql_alpha = 1.0  # conservative penalty weight


class CQLLearner(DQNLearner):
    """DQN TD loss + the CQL(H) conservative penalty, one jitted update."""

    def loss_fn(self, params, batch):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rllib.core.rl_module import forward_q

        td_loss, metrics = super().loss_fn(params, batch)
        q_all = forward_q(params, batch["obs"])
        q_data = jnp.take_along_axis(
            q_all, batch["actions"][:, None], axis=-1
        )[:, 0]
        # logsumexp over actions ~= soft-max value of the CURRENT net; its
        # gap to the logged action's value is the OOD overestimation the
        # penalty minimizes.
        cql_gap = jnp.mean(jax.nn.logsumexp(q_all, axis=-1) - q_data)
        loss = td_loss + self.cfg["cql_alpha"] * cql_gap
        return loss, {
            **metrics,
            "cql_gap": cql_gap,
            "td_loss": td_loss,
            "total_loss": loss,
        }


class CQL(Algorithm):
    policy_kind = "q"

    def __init__(self, config: AlgorithmConfig):
        if config.offline_input is None:
            raise ValueError(
                "CQL requires offline data: config.offline_data(input_=...)"
            )
        super().__init__(config)
        rows = materialize_offline(config.offline_input)
        n = len(rows)
        self._obs = np.asarray(
            [r["obs"] for r in rows], dtype=np.float32
        ).reshape(n, -1)
        self._acts = validate_discrete_actions(
            np.asarray([r["actions"] for r in rows]), self.num_actions, "CQL"
        )
        self._rewards = np.asarray(
            [float(r.get("rewards", 0.0)) for r in rows], dtype=np.float32
        )
        self._next_obs = np.asarray(
            [r.get("next_obs", r["obs"]) for r in rows], dtype=np.float32
        ).reshape(n, -1)
        self._dones = np.asarray(
            [bool(r.get("dones", False)) for r in rows], dtype=np.float32
        )
        self._rng = np.random.RandomState(config.seed)
        self._updates_since_target_sync = 0

    def _learner_builder(self, obs_dim: int, num_actions: int) -> Callable[[], Any]:
        from ray_tpu.rllib.core.rl_module import RLModuleSpec

        cfg = self.config
        spec = RLModuleSpec(
            obs_dim=obs_dim,
            num_actions=num_actions,
            hidden=tuple(cfg.model.get("hidden", (64, 64))),
        )
        loss_cfg = {
            "gamma": cfg.gamma,
            "double_q": cfg.double_q,
            "cql_alpha": cfg.cql_alpha,
        }
        lr, grad_clip, seed = cfg.lr, cfg.grad_clip, cfg.seed

        def build():
            return CQLLearner(spec, loss_cfg, lr=lr, grad_clip=grad_clip, seed=seed)

        return build

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        # Target-network state lives in-process (same constraint as DQN).
        learner = self.learner_group._local
        assert learner is not None, "CQL requires num_learners=0 (local learner)"
        metrics: Dict[str, float] = {}
        for _ in range(cfg.updates_per_iteration):
            idx = self._rng.randint(0, len(self._obs), size=cfg.train_batch_size)
            metrics = learner.update_from_batch(
                {
                    "obs": self._obs[idx],
                    "actions": self._acts[idx],
                    "rewards": self._rewards[idx],
                    "next_obs": self._next_obs[idx],
                    "dones": self._dones[idx],
                }
            )
            self._updates_since_target_sync += 1
            if (
                self._updates_since_target_sync
                >= cfg.target_network_update_freq_updates
            ):
                learner.sync_target()
                self._updates_since_target_sync = 0
        self._sync_weights()
        return {
            **{k: float(v) for k, v in metrics.items()},
            "num_offline_rows": len(self._obs),
        }

    def evaluate(self, num_steps: int = 256) -> Dict[str, Any]:
        batches = self.env_runner_group.sample(num_steps, epsilon=0.0)
        return self._episode_metrics(batches)
