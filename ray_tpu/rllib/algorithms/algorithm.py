"""Algorithm: the top-level RL training loop object.

Analog of rllib/algorithms/algorithm.py:210 (training_step:1589): owns an
EnvRunnerGroup and a LearnerGroup, `train()` runs one iteration and returns
a result dict. `as_trainable()` adapts it to the Tune function-trainable
protocol so `Tuner(PPOConfig()...build_algo-less)` works the same way the
reference couples Algorithm to Tune.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner_group import LearnerGroup
from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup


class Algorithm:
    # Subclasses set these.
    policy_kind = "pi_vf"
    supports_multi_agent = False

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.iteration = 0
        self._env_steps_total = 0
        self._start_time = time.time()
        self._weights_version = 0

        self.multi_agent = bool(config.policies)
        if self.multi_agent and not type(self).supports_multi_agent:
            raise ValueError(
                f"{type(self).__name__} does not implement multi-agent "
                "training; use PPO or drop .multi_agent() from the config"
            )
        extra = None
        runner_cls = None
        if self.multi_agent:
            from ray_tpu.rllib.env.multi_agent_env_runner import (
                MultiAgentEnvRunner,
            )

            runner_cls = MultiAgentEnvRunner
            extra = {
                "policies": list(config.policies),
                "policy_mapping_fn": config.policy_mapping_fn,
            }
        self.env_runner_group = EnvRunnerGroup(
            env=config.env,
            env_config=config.env_config,
            num_env_runners=config.num_env_runners,
            num_envs_per_env_runner=config.num_envs_per_env_runner,
            policy_kind=self.policy_kind,
            module_spec_dict=self._module_spec_dict(),
            seed=config.seed,
            restart_failed=config.restart_failed_env_runners,
            sample_timeout_s=config.sample_timeout_s,
            runner_cls=runner_cls,
            extra_ctor_kwargs=extra,
        )
        if self.multi_agent:
            # {policy_id: (obs_dim, num_actions)} -> one learner group per
            # policy (the reference's MultiRLModule, split by module so
            # policies with different spaces stay independent jit programs).
            spaces = self.env_runner_group.get_spaces()
            self.policy_spaces = spaces
            self.learner_groups: Dict[str, LearnerGroup] = {
                pid: LearnerGroup(
                    self._learner_builder(od, na),
                    num_learners=config.num_learners,
                    num_cpus_per_learner=config.num_cpus_per_learner,
                    num_tpus_per_learner=config.num_tpus_per_learner,
                )
                for pid, (od, na) in spaces.items()
            }
            self.learner_group = None
            self.obs_dim = self.num_actions = None
        else:
            obs_dim, num_actions = self.env_runner_group.get_spaces()
            self.obs_dim, self.num_actions = obs_dim, num_actions
            self.learner_group = LearnerGroup(
                self._learner_builder(obs_dim, num_actions),
                num_learners=config.num_learners,
                num_cpus_per_learner=config.num_cpus_per_learner,
                num_tpus_per_learner=config.num_tpus_per_learner,
            )
        self._sync_weights()

    # -- subclass hooks ------------------------------------------------------

    def _module_spec_dict(self) -> Dict[str, Any]:
        m = self.config.model
        return {
            "hidden": tuple(m.get("hidden", (64, 64))),
            "vf_share_layers": bool(m.get("vf_share_layers", False)),
        }

    def _learner_builder(self, obs_dim: int, num_actions: int) -> Callable[[], Any]:
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    # -- public API ----------------------------------------------------------

    def train(self) -> Dict[str, Any]:
        self.iteration += 1
        t0 = time.time()
        metrics = self.training_step()
        elapsed = time.time() - t0
        result = {
            "training_iteration": self.iteration,
            "time_this_iter_s": elapsed,
            "time_total_s": time.time() - self._start_time,
            "num_env_steps_sampled_lifetime": self._env_steps_total,
            **metrics,
        }
        return result

    def _sync_weights(self) -> None:
        self._weights_version += 1
        if self.multi_agent:
            weights = {
                pid: lg.get_weights() for pid, lg in self.learner_groups.items()
            }
        else:
            weights = self.learner_group.get_weights()
        self.env_runner_group.sync_weights(weights, self._weights_version)

    def _episode_metrics(self, batches: List[Dict[str, Any]]) -> Dict[str, float]:
        stats = []
        for b in batches:
            stats.extend(b.get("episode_stats", []))
        if not stats:
            return {
                "episode_return_mean": float("nan"),
                "episode_len_mean": float("nan"),
            }
        returns = [s[0] for s in stats]
        lens = [s[1] for s in stats]
        return {
            "episode_return_mean": float(np.mean(returns)),
            "episode_return_max": float(np.max(returns)),
            "episode_return_min": float(np.min(returns)),
            "episode_len_mean": float(np.mean(lens)),
        }

    # -- checkpointing (reference: Algorithm.save/restore) -------------------

    def save(self, checkpoint_dir: str) -> str:
        os.makedirs(checkpoint_dir, exist_ok=True)
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        if self.multi_agent:
            learner_state = {
                pid: lg.get_state() for pid, lg in self.learner_groups.items()
            }
        else:
            learner_state = self.learner_group.get_state()
        state = {
            "learner": learner_state,
            "multi_agent": self.multi_agent,
            "iteration": self.iteration,
            "env_steps": self._env_steps_total,
            "config": self.config.to_dict(),
        }
        with open(path, "wb") as f:
            # cloudpickle: multi-agent configs hold callables (env factory,
            # policy_mapping_fn), often lambdas/closures plain pickle rejects.
            import cloudpickle

            cloudpickle.dump(state, f)
        return checkpoint_dir

    def restore(self, checkpoint_dir: str) -> None:
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        if self.multi_agent:
            for pid, lg in self.learner_groups.items():
                lg.set_state(state["learner"][pid])
        else:
            self.learner_group.set_state(state["learner"])
        self.iteration = state["iteration"]
        self._env_steps_total = state["env_steps"]
        self._sync_weights()

    def stop(self) -> None:
        self.env_runner_group.stop()
        if self.multi_agent:
            for lg in self.learner_groups.values():
                lg.shutdown()
        else:
            self.learner_group.shutdown()

    # -- Tune integration ----------------------------------------------------

    @classmethod
    def as_trainable(
        cls, base_config: AlgorithmConfig, *, stop: Optional[Dict[str, Any]] = None
    ) -> Callable[[Dict[str, Any]], None]:
        """Returns a Tune function-trainable: hyperparams from the trial
        config are applied over base_config via .training()."""
        stop = stop or {"training_iteration": 10}

        def trainable(trial_config: Dict[str, Any]) -> None:
            from ray_tpu import train as train_session

            cfg = base_config.copy()
            for k, v in (trial_config or {}).items():
                if hasattr(cfg, k):
                    setattr(cfg, k, v)
            algo = cls(config=cfg)
            try:
                while True:
                    result = algo.train()
                    train_session.report(result)
                    if any(
                        result.get(k) is not None and result[k] >= v
                        for k, v in stop.items()
                    ):
                        break
            finally:
                algo.stop()

        trainable.__name__ = cls.__name__
        return trainable


def gae_advantages(
    rewards: np.ndarray,
    values: np.ndarray,
    terminateds: np.ndarray,
    truncateds: np.ndarray,
    bootstrap_value: np.ndarray,
    gamma: float,
    lam: float,
    boundary_values: Optional[np.ndarray] = None,
):
    """Generalized advantage estimation over time-major [T, N] arrays
    (reference: rllib/evaluation/postprocessing.py compute_gae_for_sample_batch,
    vectorized). Termination zeroes the bootstrap; truncation bootstraps with
    V(final_obs) (`boundary_values`, computed by the env runner) — NOT with
    the next row's value, which belongs to the next episode after autoreset."""
    T, N = rewards.shape
    adv = np.zeros((T, N), dtype=np.float32)
    if boundary_values is None:
        boundary_values = np.zeros((T, N), dtype=np.float32)
    next_value = bootstrap_value.astype(np.float32)
    gae = np.zeros(N, dtype=np.float32)
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - terminateds[t].astype(np.float32)
        boundary = np.logical_or(terminateds[t], truncateds[t])
        nv = np.where(truncateds[t], boundary_values[t], next_value)
        delta = rewards[t] + gamma * nv * nonterminal - values[t]
        gae = delta + gamma * lam * nonterminal * np.where(boundary, 0.0, 1.0) * gae
        adv[t] = gae
        next_value = values[t]
    returns = adv + values
    return adv, returns
