"""DQN: double-DQN with target network and uniform replay.

Analog of rllib/algorithms/dqn/ (dqn.py, dqn_learner, replay): env runners
explore epsilon-greedily into a replay buffer; the learner does double-DQN
TD updates on one jitted step; the target net refreshes every
target_network_update_freq env steps.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import RLModuleSpec, forward_q, init_q
from ray_tpu.rllib.utils.replay_buffer import ReplayBuffer


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=DQN)
        self.lr = 5e-4
        self.train_batch_size = 32
        self.replay_buffer_capacity = 50_000
        self.num_steps_sampled_before_learning_starts = 1000
        self.target_network_update_freq = 500  # env steps
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_timesteps = 10_000
        self.double_q = True
        self.updates_per_iteration = 32
        self.rollout_fragment_length = 4


class DQNLearner(Learner):
    def __init__(self, spec: RLModuleSpec, cfg: Dict[str, Any], **kw):
        self.cfg = cfg
        super().__init__(spec, **kw)
        self.target_params = self.params

    def init_params(self, rng):
        return init_q(rng, self.spec)

    def loss_fn(self, params, batch):
        import jax.numpy as jnp

        q_all = forward_q(params, batch["obs"])
        q = jnp.take_along_axis(q_all, batch["actions"][:, None], axis=-1)[:, 0]
        q_next_target = forward_q(batch["_target_params"], batch["next_obs"])
        if self.cfg["double_q"]:
            # Online net picks the argmax, target net evaluates it.
            q_next_online = forward_q(params, batch["next_obs"])
            best = jnp.argmax(q_next_online, axis=-1)
            q_next = jnp.take_along_axis(q_next_target, best[:, None], axis=-1)[:, 0]
        else:
            q_next = q_next_target.max(axis=-1)
        target = batch["rewards"] + self.cfg["gamma"] * (1.0 - batch["dones"]) * q_next
        import jax

        target = jax.lax.stop_gradient(target)
        # Huber loss (reference dqn uses huber by default).
        err = q - target
        huber = jnp.where(jnp.abs(err) < 1.0, 0.5 * err * err, jnp.abs(err) - 0.5)
        loss = jnp.mean(huber)
        return loss, {"qf_loss": loss, "q_mean": jnp.mean(q)}

    def update_from_batch(self, batch):
        batch = dict(batch)
        batch["_target_params"] = self.target_params
        return super().update_from_batch(batch)

    def sync_target(self) -> None:
        self.target_params = self.params


class DQN(Algorithm):
    policy_kind = "q"

    def __init__(self, config: AlgorithmConfig):
        super().__init__(config)
        self.replay = ReplayBuffer(
            config.replay_buffer_capacity, self.obs_dim, seed=config.seed
        )
        self._steps_since_target_sync = 0

    def _learner_builder(self, obs_dim: int, num_actions: int) -> Callable[[], Any]:
        cfg = self.config
        spec = RLModuleSpec(
            obs_dim=obs_dim,
            num_actions=num_actions,
            hidden=tuple(cfg.model.get("hidden", (64, 64))),
        )
        loss_cfg = {"gamma": cfg.gamma, "double_q": cfg.double_q}
        lr, grad_clip, seed = cfg.lr, cfg.grad_clip, cfg.seed

        def build():
            return DQNLearner(spec, loss_cfg, lr=lr, grad_clip=grad_clip, seed=seed)

        return build

    @property
    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._env_steps_total / max(1, c.epsilon_timesteps))
        return c.epsilon_initial + frac * (c.epsilon_final - c.epsilon_initial)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        # DQN's learner is local-only (target-net state lives in-process).
        learner = self.learner_group._local
        assert learner is not None, "DQN requires num_learners=0 (local learner)"

        batches = self.env_runner_group.sample(
            cfg.rollout_fragment_length, epsilon=self._epsilon
        )
        new_steps = sum(b["env_steps"] for b in batches)
        self._env_steps_total += new_steps
        self._steps_since_target_sync += new_steps
        for b in batches:
            self.replay.add_batch(b)

        metrics: Dict[str, float] = {}
        if len(self.replay) >= cfg.num_steps_sampled_before_learning_starts:
            for _ in range(cfg.updates_per_iteration):
                metrics = learner.update_from_batch(
                    self.replay.sample(cfg.train_batch_size)
                )
            if self._steps_since_target_sync >= cfg.target_network_update_freq:
                learner.sync_target()
                self._steps_since_target_sync = 0
            self._sync_weights()
        return {
            **self._episode_metrics(batches),
            **metrics,
            "epsilon": self._epsilon,
            "replay_size": len(self.replay),
        }
