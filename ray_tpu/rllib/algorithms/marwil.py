"""MARWIL: monotonic advantage re-weighted imitation learning.

Analog of rllib/algorithms/marwil/ (marwil.py + marwil_learner): offline
imitation where each logged action's log-likelihood is weighted by
exp(beta * advantage) — better-than-average actions are imitated harder,
beta=0 degenerates to plain BC. Advantages come from Monte-Carlo returns
over the logged episodes minus the learned value baseline; the moving
average of squared advantages normalizes the exponent (the reference's
update_beta/ moving-average-sqd-adv-norm machinery, jax-style: carried as
a scalar in the learner and folded into one jitted update).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.utils.offline import materialize_offline, validate_discrete_actions
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import RLModuleSpec, forward_pi_vf, init_pi_vf


class MARWILConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=MARWIL)
        self.lr = 1e-3
        self.train_batch_size = 256
        self.updates_per_iteration = 32
        self.beta = 1.0  # 0 => plain BC
        self.vf_coeff = 1.0
        # Exponent clip guards exp() overflow on outlier advantages
        # (reference: MARWIL's 'clip exp term' behavior).
        self.max_adv_exponent = 10.0


class MARWILLearner(Learner):
    def __init__(self, spec: RLModuleSpec, cfg: Dict[str, Any], **kw):
        self.cfg = cfg
        super().__init__(spec, **kw)
        # Moving average of squared advantages (normalizes the exponent).
        self.ma_sq_adv = 1.0

    def init_params(self, rng):
        return init_pi_vf(rng, self.spec)

    def loss_fn(self, params, batch):
        import jax
        import jax.numpy as jnp

        logits, values = forward_pi_vf(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], axis=-1
        )[:, 0]
        adv = batch["returns"] - values
        # Weight = exp(beta * adv / sqrt(ma_sq_adv)); baseline gradient
        # must not flow through the weight (stop_gradient on adv).
        norm = jnp.sqrt(batch["_ma_sq_adv"]) + 1e-8
        exponent = jnp.clip(
            self.cfg["beta"] * jax.lax.stop_gradient(adv) / norm,
            -self.cfg["max_adv_exponent"],
            self.cfg["max_adv_exponent"],
        )
        weight = jnp.exp(exponent)
        policy_loss = -jnp.mean(weight * logp)
        vf_loss = jnp.mean(adv**2)
        loss = policy_loss + self.cfg["vf_coeff"] * vf_loss
        return loss, {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "total_loss": loss,
            "mean_weight": jnp.mean(weight),
            "mean_sq_adv": jnp.mean(jax.lax.stop_gradient(adv) ** 2),
        }

    def update_from_batch(self, batch):
        batch = dict(batch)
        batch["_ma_sq_adv"] = np.float32(self.ma_sq_adv)
        metrics = super().update_from_batch(batch)
        # Moving-average update outside the jitted step (a carried scalar).
        msa = float(metrics.get("mean_sq_adv", self.ma_sq_adv))
        self.ma_sq_adv = 0.99 * self.ma_sq_adv + 0.01 * msa
        return metrics


def _discounted_returns(rows: List[dict], gamma: float) -> np.ndarray:
    """Monte-Carlo return per row over the logged episode boundaries
    (reference: offline pre-processing computes advantages from returns)."""
    rewards = np.asarray([float(r.get("rewards", 0.0)) for r in rows])
    dones = np.asarray([bool(r.get("dones", False)) for r in rows])
    returns = np.zeros(len(rows), dtype=np.float32)
    acc = 0.0
    for i in range(len(rows) - 1, -1, -1):
        if dones[i]:
            acc = 0.0
        acc = rewards[i] + gamma * acc
        returns[i] = acc
    return returns


class MARWIL(Algorithm):
    policy_kind = "pi_vf"

    def __init__(self, config: AlgorithmConfig):
        if config.offline_input is None:
            raise ValueError(
                "MARWIL requires offline data: config.offline_data(input_=...)"
            )
        super().__init__(config)
        rows = materialize_offline(config.offline_input)
        self._obs = np.asarray(
            [r["obs"] for r in rows], dtype=np.float32
        ).reshape(len(rows), -1)
        self._acts = validate_discrete_actions(
            np.asarray([r["actions"] for r in rows]), self.num_actions, "MARWIL"
        )
        self._returns = _discounted_returns(rows, config.gamma)
        self._rng = np.random.RandomState(config.seed)

    def _learner_builder(self, obs_dim: int, num_actions: int) -> Callable[[], Any]:
        cfg = self.config
        spec = RLModuleSpec(
            obs_dim=obs_dim,
            num_actions=num_actions,
            hidden=tuple(cfg.model.get("hidden", (64, 64))),
        )
        loss_cfg = {
            "beta": cfg.beta,
            "vf_coeff": cfg.vf_coeff,
            "max_adv_exponent": cfg.max_adv_exponent,
        }
        lr, grad_clip, seed = cfg.lr, cfg.grad_clip, cfg.seed

        def build():
            return MARWILLearner(spec, loss_cfg, lr=lr, grad_clip=grad_clip, seed=seed)

        return build

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        metrics: Dict[str, float] = {}
        for _ in range(cfg.updates_per_iteration):
            idx = self._rng.randint(0, len(self._obs), size=cfg.train_batch_size)
            metrics = self.learner_group.update_from_batch(
                {
                    "obs": self._obs[idx],
                    "actions": self._acts[idx],
                    "returns": self._returns[idx],
                }
            )
        self._sync_weights()
        return {
            **{k: float(v) for k, v in metrics.items()},
            "num_offline_rows": len(self._obs),
        }

    def evaluate(self, num_steps: int = 256) -> Dict[str, Any]:
        batches = self.env_runner_group.sample(num_steps)
        return self._episode_metrics(batches)
