"""SAC: soft actor-critic for continuous (Box) action spaces.

Analog of rllib/algorithms/sac/ (sac.py, sac_learner, default_sac_rl_module):
squashed-Gaussian actor, twin Q critics with polyak-averaged targets, and
automatic entropy-temperature tuning against a target entropy of -act_dim.
Off-policy: env runners explore stochastically into a uniform replay buffer;
the learner runs jitted critic/actor/alpha updates.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import RLModuleSpec, init_sac, sac_pi, sac_q
from ray_tpu.rllib.utils.replay_buffer import ReplayBuffer


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=SAC)
        self.lr = 3e-4
        self.train_batch_size = 256
        self.replay_buffer_capacity = 100_000
        self.num_steps_sampled_before_learning_starts = 1500
        self.tau = 0.005  # polyak target-update coefficient
        self.target_entropy = None  # default: -act_dim
        self.initial_alpha = 1.0
        self.updates_per_iteration = 32
        self.rollout_fragment_length = 4


class SACLearner(Learner):
    """One update = twin-critic TD step + actor step + alpha step, all in
    the single jitted loss (losses are summed; their parameter sets are
    disjoint, so gradients don't cross-contaminate — the standard single
    -optimizer formulation)."""

    def __init__(self, spec: RLModuleSpec, cfg: Dict[str, Any], **kw):
        self.cfg = cfg
        super().__init__(spec, **kw)
        self.target_params = {"q1": self.params["q1"], "q2": self.params["q2"]}

    def init_params(self, rng):
        import jax.numpy as jnp

        params = init_sac(rng, self.spec)
        params["log_alpha"] = jnp.asarray(
            jnp.log(self.cfg.get("initial_alpha", 1.0)), params["log_alpha"].dtype
        )
        return params

    def loss_fn(self, params, batch):
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        limit = self.spec.act_limit
        alpha = jnp.exp(params["log_alpha"])

        # -- critic loss (targets from the target twin-min + entropy bonus)
        next_act, next_logp = sac_pi(
            params, batch["next_obs"], batch["_rng_next"], limit
        )
        tq1, tq2 = sac_q(batch["_target_params"], batch["next_obs"], next_act)
        target_v = jnp.minimum(tq1, tq2) - jax.lax.stop_gradient(alpha) * next_logp
        target = batch["rewards"] + cfg["gamma"] * (1.0 - batch["dones"]) * target_v
        target = jax.lax.stop_gradient(target)
        q1, q2 = sac_q(params, batch["obs"], batch["actions"])
        critic_loss = jnp.mean((q1 - target) ** 2) + jnp.mean((q2 - target) ** 2)

        # -- actor loss (reparameterized; critics frozen via stop_gradient)
        frozen_q = jax.lax.stop_gradient({"q1": params["q1"], "q2": params["q2"]})
        act, logp = sac_pi(params, batch["obs"], batch["_rng_pi"], limit)
        aq1, aq2 = sac_q(frozen_q, batch["obs"], act)
        actor_loss = jnp.mean(
            jax.lax.stop_gradient(alpha) * logp - jnp.minimum(aq1, aq2)
        )

        # -- temperature loss (drive entropy toward the target)
        alpha_loss = -jnp.mean(
            params["log_alpha"] * jax.lax.stop_gradient(logp + cfg["target_entropy"])
        )

        loss = critic_loss + actor_loss + alpha_loss
        return loss, {
            "critic_loss": critic_loss,
            "actor_loss": actor_loss,
            "alpha_loss": alpha_loss,
            "alpha": alpha,
            "q1_mean": jnp.mean(q1),
            "entropy": -jnp.mean(logp),
        }

    def update_from_batch(self, batch):
        batch = dict(batch)
        batch["_target_params"] = self.target_params
        batch["_rng_next"] = self._next_rng()
        batch["_rng_pi"] = self._next_rng()
        metrics = super().update_from_batch(batch)
        self._polyak()
        return metrics

    def _polyak(self) -> None:
        import jax

        tau = self.cfg["tau"]
        online = {"q1": self.params["q1"], "q2": self.params["q2"]}
        self.target_params = jax.tree_util.tree_map(
            lambda t, o: (1.0 - tau) * t + tau * o, self.target_params, online
        )


class SAC(Algorithm):
    policy_kind = "sac"

    def __init__(self, config: AlgorithmConfig):
        super().__init__(config)
        act_dim, _ = self.env_runner_group.get_act_info()
        self.replay = ReplayBuffer(
            config.replay_buffer_capacity,
            self.obs_dim,
            seed=config.seed,
            act_dim=act_dim,
        )

    def _module_spec_dict(self) -> Dict[str, Any]:
        m = self.config.model
        return {"hidden": tuple(m.get("hidden", (256, 256)))}

    def _learner_builder(self, obs_dim: int, num_actions: int) -> Callable[[], Any]:
        cfg = self.config
        act_dim, act_limit = self.env_runner_group.get_act_info()
        if not act_dim:
            raise ValueError("SAC requires a continuous (Box) action space")
        spec = RLModuleSpec(
            obs_dim=obs_dim,
            num_actions=0,
            hidden=tuple(cfg.model.get("hidden", (256, 256))),
            act_dim=act_dim,
            act_limit=act_limit,
        )
        target_entropy = (
            cfg.target_entropy if cfg.target_entropy is not None else -float(act_dim)
        )
        loss_cfg = {
            "gamma": cfg.gamma,
            "tau": cfg.tau,
            "target_entropy": target_entropy,
            "initial_alpha": cfg.initial_alpha,
        }
        lr, grad_clip, seed = cfg.lr, cfg.grad_clip, cfg.seed

        def build():
            return SACLearner(spec, loss_cfg, lr=lr, grad_clip=grad_clip, seed=seed)

        return build

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        learner = self.learner_group._local
        assert learner is not None, "SAC requires num_learners=0 (local learner)"

        warmup = (
            self._env_steps_total < cfg.num_steps_sampled_before_learning_starts
        )
        batches = self.env_runner_group.sample(
            cfg.rollout_fragment_length, random_actions=warmup
        )
        self._env_steps_total += sum(b["env_steps"] for b in batches)
        for b in batches:
            self.replay.add_batch(b)

        metrics: Dict[str, float] = {}
        if len(self.replay) >= cfg.num_steps_sampled_before_learning_starts:
            for _ in range(cfg.updates_per_iteration):
                metrics = learner.update_from_batch(
                    self.replay.sample(cfg.train_batch_size)
                )
            self._sync_weights()
        return {
            **self._episode_metrics(batches),
            **{k: float(v) for k, v in metrics.items()},
            "replay_size": len(self.replay),
        }
