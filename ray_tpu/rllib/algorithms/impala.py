"""IMPALA: asynchronous sampling with V-trace off-policy correction.

Analog of rllib/algorithms/impala/impala.py (async pipeline + weight
broadcast at impala.py:1152–1217): env runners sample continuously (no sync
barrier); the learner consumes batches as they land, corrects for policy lag
with V-trace (Espeholt et al. 2018), and broadcasts fresh weights to each
runner as its next sample request is issued. APPO = same pipeline with the
PPO surrogate on top of V-trace advantages.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import RLModuleSpec, forward_pi_vf, init_pi_vf


class IMPALAConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or IMPALA)
        self.lr = 5e-4
        self.rollout_fragment_length = 50
        self.vtrace_clip_rho_threshold = 1.0
        self.vtrace_clip_c_threshold = 1.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.batches_per_iteration = 8
        self.broadcast_interval = 1  # updates between weight pushes
        self.num_env_runners = 2


def _vtrace(
    behavior_logp,
    target_logp,
    rewards,
    values,
    bootstrap_value,
    dones,
    gamma,
    clip_rho,
    clip_c,
):
    """V-trace targets/advantages over time-major [T, B] jnp arrays, computed
    inside the jitted loss (lax.scan over reversed time)."""
    import jax
    import jax.numpy as jnp

    rhos = jnp.exp(target_logp - behavior_logp)
    clipped_rhos = jnp.minimum(clip_rho, rhos)
    clipped_cs = jnp.minimum(clip_c, rhos)
    # dones = terminated | truncated: truncation also cuts the recursion
    # (the next row belongs to a different episode after autoreset).
    discounts = gamma * (1.0 - dones)
    values_tp1 = jnp.concatenate([values[1:], bootstrap_value[None]], axis=0)
    deltas = clipped_rhos * (rewards + discounts * values_tp1 - values)

    def scan_fn(acc, xs):
        delta, discount, c = xs
        acc = delta + discount * c * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        scan_fn,
        jnp.zeros_like(bootstrap_value),
        (deltas[::-1], discounts[::-1], clipped_cs[::-1]),
    )
    vs_minus_v = vs_minus_v[::-1]
    vs = values + vs_minus_v
    vs_tp1 = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_advantages = clipped_rhos * (rewards + discounts * vs_tp1 - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_advantages)


class IMPALALearner(Learner):
    def __init__(self, spec: RLModuleSpec, cfg: Dict[str, Any], **kw):
        self.cfg = cfg
        super().__init__(spec, **kw)

    def init_params(self, rng):
        return init_pi_vf(rng, self.spec)

    def _policy_loss(self, target_logp, behavior_logp, pg_adv):
        import jax.numpy as jnp

        return -jnp.mean(target_logp * pg_adv)

    def loss_fn(self, params, batch):
        import jax
        import jax.numpy as jnp

        c = self.cfg
        T, B = batch["rewards"].shape
        obs = batch["obs"].reshape(T * B, -1)
        logits, values = forward_pi_vf(params, obs)
        logits = logits.reshape(T, B, -1)
        values = values.reshape(T, B)
        logp_all = jax.nn.log_softmax(logits)
        target_logp = jnp.take_along_axis(
            logp_all, batch["actions"][..., None], axis=-1
        )[..., 0]

        dones = jnp.logical_or(
            batch["terminateds"], batch["truncateds"]
        ).astype(jnp.float32)
        vs, pg_adv = _vtrace(
            batch["behavior_logp"],
            target_logp,
            batch["rewards"],
            jax.lax.stop_gradient(values),
            batch["bootstrap_value"],
            dones,
            c["gamma"],
            c["clip_rho"],
            c["clip_c"],
        )
        policy_loss = self._policy_loss(
            target_logp, batch["behavior_logp"], pg_adv
        )
        vf_loss = 0.5 * jnp.mean(jnp.square(values - vs))
        probs = jax.nn.softmax(logits)
        entropy = -jnp.mean(jnp.sum(probs * logp_all, axis=-1))
        loss = policy_loss + c["vf_loss_coeff"] * vf_loss - c["entropy_coeff"] * entropy
        return loss, {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
        }


class IMPALA(Algorithm):
    policy_kind = "pi_vf"

    def _learner_builder(self, obs_dim: int, num_actions: int) -> Callable[[], Any]:
        cfg = self.config
        spec = RLModuleSpec(
            obs_dim=obs_dim,
            num_actions=num_actions,
            hidden=tuple(cfg.model.get("hidden", (64, 64))),
        )
        loss_cfg = {
            "gamma": cfg.gamma,
            "clip_rho": cfg.vtrace_clip_rho_threshold,
            "clip_c": cfg.vtrace_clip_c_threshold,
            "vf_loss_coeff": cfg.vf_loss_coeff,
            "entropy_coeff": cfg.entropy_coeff,
        }
        lr, grad_clip, seed = cfg.lr, cfg.grad_clip, cfg.seed

        def build():
            return IMPALALearner(spec, loss_cfg, lr=lr, grad_clip=grad_clip, seed=seed)

        return build

    def __init__(self, config: AlgorithmConfig):
        if config.num_env_runners < 1:
            raise ValueError("IMPALA requires num_env_runners >= 1")
        super().__init__(config)
        self._inflight: Dict[Any, tuple] = {}  # ref -> (actor_idx, submit_t)
        self._updates_since_broadcast: Dict[int, int] = {}

    def _ensure_inflight(self) -> None:
        """Heal dead/replaced runners, then keep one sample request in flight
        per healthy runner."""
        import time as _time

        cfg = self.config
        self.env_runner_group._heal()
        mgr = self.env_runner_group._manager
        healthy = set(mgr.healthy_actor_ids())
        # Drop requests pinned to runners that are gone (their refs may never
        # resolve) and requests that have outlived the sample timeout (hung
        # runner: mark unhealthy so _heal replaces it next round).
        now = _time.monotonic()
        for ref, (idx, t0) in list(self._inflight.items()):
            if idx not in healthy:
                del self._inflight[ref]
            elif now - t0 > cfg.sample_timeout_s:
                self.env_runner_group.mark_unhealthy(idx)
                del self._inflight[ref]
        have = {idx for idx, _ in self._inflight.values()}
        for i in healthy - have:
            try:
                ref = self.env_runner_group.submit_sample(
                    i, cfg.rollout_fragment_length
                )
            except Exception:
                self.env_runner_group.mark_unhealthy(i)
                continue
            self._inflight[ref] = (i, now)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        batches_done: List[Dict[str, Any]] = []
        metrics: Dict[str, float] = {}
        stale_total = 0
        while len(batches_done) < cfg.batches_per_iteration:
            self._ensure_inflight()
            if not self._inflight:
                raise RuntimeError("no healthy env runners for IMPALA")
            ready, _ = ray_tpu.wait(
                list(self._inflight),
                num_returns=1,
                timeout=min(5.0, cfg.sample_timeout_s),
            )
            if not ready:
                continue
            ref = ready[0]
            actor_idx, _t0 = self._inflight.pop(ref)
            try:
                batch = ray_tpu.get(ref)
            except Exception:
                self.env_runner_group.mark_unhealthy(actor_idx)
                continue
            self._env_steps_total += batch["env_steps"]
            stale_total += self._weights_version - batch["weights_version"]

            train_batch = {
                "obs": batch["obs"],
                "actions": batch["actions"],
                "behavior_logp": batch["logp"],
                "rewards": batch["rewards"],
                "terminateds": batch["terminateds"],
                "truncateds": batch["truncateds"],
                "bootstrap_value": batch["bootstrap_value"],
            }
            metrics = self.learner_group.update_from_batch(
                train_batch, time_major=True
            )
            batches_done.append(batch)

            # Async weight push to this runner, then immediately resubmit its
            # next sample so it never idles (reference impala.py broadcast).
            n = self._updates_since_broadcast.get(actor_idx, 0) + 1
            if n >= cfg.broadcast_interval:
                self._weights_version += 1
                self.env_runner_group._manager.actors[actor_idx].set_weights.remote(
                    self.learner_group.get_weights(), self._weights_version
                )
                self._updates_since_broadcast[actor_idx] = 0
            else:
                self._updates_since_broadcast[actor_idx] = n
            import time as _time

            try:
                new_ref = self.env_runner_group.submit_sample(
                    actor_idx, cfg.rollout_fragment_length
                )
                self._inflight[new_ref] = (actor_idx, _time.monotonic())
            except Exception:
                self.env_runner_group.mark_unhealthy(actor_idx)
        return {
            **self._episode_metrics(batches_done),
            **metrics,
            "mean_weight_staleness": stale_total / max(1, len(batches_done)),
        }


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__(algo_class=APPO)
        self.clip_param = 0.2


class APPOLearner(IMPALALearner):
    def _policy_loss(self, target_logp, behavior_logp, pg_adv):
        # PPO clipped surrogate on V-trace advantages (reference APPO loss).
        import jax.numpy as jnp

        c = self.cfg
        ratio = jnp.exp(target_logp - behavior_logp)
        surr1 = ratio * pg_adv
        surr2 = jnp.clip(ratio, 1 - c["clip_param"], 1 + c["clip_param"]) * pg_adv
        return -jnp.mean(jnp.minimum(surr1, surr2))


class APPO(IMPALA):
    def _learner_builder(self, obs_dim: int, num_actions: int) -> Callable[[], Any]:
        cfg = self.config
        spec = RLModuleSpec(
            obs_dim=obs_dim,
            num_actions=num_actions,
            hidden=tuple(cfg.model.get("hidden", (64, 64))),
        )
        loss_cfg = {
            "gamma": cfg.gamma,
            "clip_rho": cfg.vtrace_clip_rho_threshold,
            "clip_c": cfg.vtrace_clip_c_threshold,
            "vf_loss_coeff": cfg.vf_loss_coeff,
            "entropy_coeff": cfg.entropy_coeff,
            "clip_param": cfg.clip_param,
        }
        lr, grad_clip, seed = cfg.lr, cfg.grad_clip, cfg.seed

        def build():
            return APPOLearner(spec, loss_cfg, lr=lr, grad_clip=grad_clip, seed=seed)

        return build
