"""AlgorithmConfig: fluent builder for algorithm hyperparameters.

Analog of rllib/algorithms/algorithm_config.py:117 — the same chained-setter
API (.environment().env_runners().training().learners()), with TPU-relevant
resource knobs. `.build_algo()` constructs the Algorithm.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Optional, Tuple, Type, Union


class AlgorithmConfig:
    def __init__(self, algo_class: Optional[Type] = None):
        self.algo_class = algo_class
        # environment()
        self.env: Union[str, Callable, None] = None
        self.env_config: Dict[str, Any] = {}
        # env_runners()
        self.num_env_runners: int = 0
        self.num_envs_per_env_runner: int = 1
        self.rollout_fragment_length: int = 200
        self.sample_timeout_s: float = 60.0
        # training()
        self.lr: float = 5e-4
        self.gamma: float = 0.99
        self.train_batch_size: int = 4000
        self.grad_clip: Optional[float] = 40.0
        self.model: Dict[str, Any] = {"hidden": (64, 64), "vf_share_layers": False}
        # learners()
        self.num_learners: int = 0
        self.num_cpus_per_learner: float = 1.0
        self.num_tpus_per_learner: float = 0.0
        # offline_data()
        self.offline_input = None
        # debugging()
        self.seed: int = 0

        # Multi-agent (reference: AlgorithmConfig.multi_agent): None/empty ->
        # single-agent mode.
        self.policies = None
        self.policy_mapping_fn = None
        # fault_tolerance()
        self.restart_failed_env_runners: bool = True

    # -- chained setters (reference API shape) -------------------------------

    def environment(self, env=None, *, env_config: Optional[dict] = None):
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = dict(env_config)
        return self

    def env_runners(
        self,
        *,
        num_env_runners: Optional[int] = None,
        num_envs_per_env_runner: Optional[int] = None,
        rollout_fragment_length: Optional[int] = None,
        sample_timeout_s: Optional[float] = None,
    ):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if sample_timeout_s is not None:
            self.sample_timeout_s = sample_timeout_s
        return self

    def offline_data(self, *, input_=None):
        """Offline training input (reference: AlgorithmConfig.offline_data):
        a ray_tpu.data Dataset (or list of row dicts) of {obs, actions}
        transitions consumed instead of env rollouts."""
        if input_ is not None:
            self.offline_input = input_
        return self

    def training(self, **kwargs):
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise TypeError(f"unknown training option {k!r}")
            setattr(self, k, v)
        return self

    def learners(
        self,
        *,
        num_learners: Optional[int] = None,
        num_cpus_per_learner: Optional[float] = None,
        num_tpus_per_learner: Optional[float] = None,
    ):
        if num_learners is not None:
            self.num_learners = num_learners
        if num_cpus_per_learner is not None:
            self.num_cpus_per_learner = num_cpus_per_learner
        if num_tpus_per_learner is not None:
            self.num_tpus_per_learner = num_tpus_per_learner
        return self

    def debugging(self, *, seed: Optional[int] = None):
        if seed is not None:
            self.seed = seed
        return self

    def multi_agent(self, *, policies=None, policy_mapping_fn=None):
        """Declare per-policy modules + the agent->policy mapping
        (reference: AlgorithmConfig.multi_agent)."""
        if policies is not None:
            self.policies = list(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    def fault_tolerance(self, *, restart_failed_env_runners: Optional[bool] = None):
        if restart_failed_env_runners is not None:
            self.restart_failed_env_runners = restart_failed_env_runners
        return self

    # -- build ---------------------------------------------------------------

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def validate(self) -> None:
        if self.env is None:
            raise ValueError("config.environment(env=...) is required")
        if self.policies and self.policy_mapping_fn is None:
            raise ValueError(
                "multi_agent(policies=...) also requires policy_mapping_fn"
            )

    def build_algo(self):
        self.validate()
        if self.algo_class is None:
            raise ValueError("no algo_class bound to this config")
        return self.algo_class(config=self.copy())

    # Back-compat alias (reference has both).
    build = build_algo

    def to_dict(self) -> Dict[str, Any]:
        d = dict(self.__dict__)
        d.pop("algo_class", None)
        return d
