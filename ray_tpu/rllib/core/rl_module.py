"""RLModule: the neural-net component of an RL algorithm, as pure JAX.

Analog of the reference's rllib/core/rl_module/ (RLModule torch/tf classes),
re-designed TPU-first: a module is a (init, forward) pair of pure functions
over a param pytree, so the learner can jit/shard the whole update and the
env-runner can jit inference — no stateful nn.Module graph.

Supported spaces: Box observations, Discrete actions (the reference's
CartPole/Atari-class configs in rllib/tuned_examples/).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class RLModuleSpec:
    """Declarative module spec (reference: rl_module/rl_module.py RLModuleSpec)."""

    obs_dim: int
    num_actions: int
    hidden: Tuple[int, ...] = (64, 64)
    # "shared" (one torso, two heads) or "separate" (independent pi/vf nets).
    vf_share_layers: bool = False
    dtype: Any = jnp.float32
    # Continuous (Box) action spaces (SAC): dimensionality and symmetric
    # bound; num_actions is 0 for continuous modules.
    act_dim: int = 0
    act_limit: float = 1.0


def _init_mlp(rng, sizes: Sequence[int], dtype) -> list:
    layers = []
    for i, (d_in, d_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        rng, k = jax.random.split(rng)
        scale = jnp.sqrt(2.0 / d_in)
        layers.append(
            {
                "w": (jax.random.normal(k, (d_in, d_out)) * scale).astype(dtype),
                "b": jnp.zeros((d_out,), dtype),
            }
        )
    return layers


def _mlp(layers: list, x, final_tanh: bool = False):
    for i, lyr in enumerate(layers):
        x = x @ lyr["w"] + lyr["b"]
        if i < len(layers) - 1:
            x = jnp.tanh(x)
    return x


def init_pi_vf(rng, spec: RLModuleSpec) -> Dict[str, Any]:
    """Policy + value params for actor-critic algorithms (PPO/IMPALA/APPO)."""
    k1, k2 = jax.random.split(rng)
    if spec.vf_share_layers:
        torso_sizes = (spec.obs_dim, *spec.hidden)
        return {
            "torso": _init_mlp(k1, torso_sizes, spec.dtype),
            "pi_head": _init_mlp(k2, (spec.hidden[-1], spec.num_actions), spec.dtype),
            "vf_head": _init_mlp(
                jax.random.fold_in(k2, 1), (spec.hidden[-1], 1), spec.dtype
            ),
        }
    return {
        "pi": _init_mlp(k1, (spec.obs_dim, *spec.hidden, spec.num_actions), spec.dtype),
        "vf": _init_mlp(k2, (spec.obs_dim, *spec.hidden, 1), spec.dtype),
    }


def forward_pi_vf(params: Dict[str, Any], obs) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (action_logits [B, A], value [B])."""
    if "torso" in params:
        h = _mlp(params["torso"], obs)
        h = jnp.tanh(h)
        logits = _mlp(params["pi_head"], h)
        value = _mlp(params["vf_head"], h)[..., 0]
    else:
        logits = _mlp(params["pi"], obs)
        value = _mlp(params["vf"], obs)[..., 0]
    return logits, value


def init_q(rng, spec: RLModuleSpec) -> Dict[str, Any]:
    """Q-network params for value-based algorithms (DQN)."""
    return {
        "q": _init_mlp(rng, (spec.obs_dim, *spec.hidden, spec.num_actions), spec.dtype)
    }


def forward_q(params: Dict[str, Any], obs) -> jnp.ndarray:
    return _mlp(params["q"], obs)


def sample_actions(rng, logits):
    """Categorical sample + logp, jit-friendly."""
    actions = jax.random.categorical(rng, logits)
    logp = jax.nn.log_softmax(logits)
    logp_a = jnp.take_along_axis(logp, actions[:, None], axis=-1)[:, 0]
    return actions, logp_a


def num_params(params) -> int:
    return int(sum(np.prod(x.shape) for x in jax.tree_util.tree_leaves(params)))


# -- SAC: squashed-Gaussian actor + twin Q (reference: sac_rl_module /
# sac_learner; continuous Box actions) --------------------------------------

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


def init_sac(rng, spec: RLModuleSpec) -> Dict[str, Any]:
    """Actor (obs -> [mu, log_std]), twin critics (obs+act -> q), and the
    learnable entropy temperature log_alpha."""
    k1, k2, k3 = jax.random.split(rng, 3)
    in_q = spec.obs_dim + spec.act_dim
    return {
        "pi": _init_mlp(k1, (spec.obs_dim, *spec.hidden, 2 * spec.act_dim), spec.dtype),
        "q1": _init_mlp(k2, (in_q, *spec.hidden, 1), spec.dtype),
        "q2": _init_mlp(k3, (in_q, *spec.hidden, 1), spec.dtype),
        "log_alpha": jnp.zeros((), spec.dtype),
    }


def sac_pi(params, obs, rng, act_limit: float):
    """Sample a squashed-Gaussian action; returns (action, logp) with the
    tanh change-of-variables correction."""
    mu_logstd = _mlp(params["pi"], obs)
    mu, log_std = jnp.split(mu_logstd, 2, axis=-1)
    log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
    std = jnp.exp(log_std)
    eps = jax.random.normal(rng, mu.shape)
    pre = mu + std * eps
    # Gaussian logp minus tanh correction (numerically stable softplus form).
    logp = (-0.5 * (eps**2) - log_std - 0.5 * jnp.log(2 * jnp.pi)).sum(-1)
    logp -= (2.0 * (jnp.log(2.0) - pre - jax.nn.softplus(-2.0 * pre))).sum(-1)
    # Jacobian of the final scaling by act_limit: without it the density is
    # that of tanh(pre), biasing the alpha auto-tune by log(act_limit)/dim.
    logp -= mu.shape[-1] * jnp.log(act_limit)
    action = jnp.tanh(pre) * act_limit
    return action, logp


def sac_pi_deterministic(params, obs, act_limit: float):
    mu_logstd = _mlp(params["pi"], obs)
    mu, _ = jnp.split(mu_logstd, 2, axis=-1)
    return jnp.tanh(mu) * act_limit


def sac_q(params, obs, act) -> Tuple[jnp.ndarray, jnp.ndarray]:
    x = jnp.concatenate([obs, act], axis=-1)
    return _mlp(params["q1"], x)[..., 0], _mlp(params["q2"], x)[..., 0]
