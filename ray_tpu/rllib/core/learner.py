"""Learner: owns params + optimizer, applies jit-compiled updates.

Analog of rllib/core/learner/learner.py:107 (update_from_batch:1074,
compute_loss:814, apply_gradients:586), TPU-first: the whole
loss→grad→apply step is one jitted function, so on a TPU host XLA fuses it
onto the MXU; data-parallel scaling shards the batch over a mesh axis inside
the same program (not DDP wrappers).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.core.rl_module import RLModuleSpec


class Learner:
    """Base learner. Subclasses define `init_params(rng)` and
    `loss_fn(params, batch) -> (loss, metrics)`; the base class jits the
    update and manages the optimizer."""

    def __init__(
        self,
        spec: RLModuleSpec,
        *,
        lr: float = 5e-4,
        grad_clip: Optional[float] = 40.0,
        optimizer: Optional[optax.GradientTransformation] = None,
        seed: int = 0,
    ):
        self.spec = spec
        self.lr = lr
        if optimizer is None:
            chain = []
            if grad_clip is not None:
                chain.append(optax.clip_by_global_norm(grad_clip))
            chain.append(optax.adam(lr))
            optimizer = optax.chain(*chain)
        self.optimizer = optimizer
        self.rng = jax.random.PRNGKey(seed)
        self.params = self.init_params(self._next_rng())
        self.opt_state = self.optimizer.init(self.params)
        self._jit_update = jax.jit(self._update_step)
        self._num_updates = 0

    # -- subclass hooks ------------------------------------------------------

    def init_params(self, rng) -> Any:
        raise NotImplementedError

    def loss_fn(self, params, batch: Dict[str, jnp.ndarray]) -> Tuple[jnp.ndarray, Dict]:
        raise NotImplementedError

    # -- update pipeline -----------------------------------------------------

    def _update_step(self, params, opt_state, batch):
        (loss, metrics), grads = jax.value_and_grad(self.loss_fn, has_aux=True)(
            params, batch
        )
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics = dict(metrics)
        metrics["total_loss"] = loss
        metrics["grad_norm"] = optax.global_norm(grads)
        return params, opt_state, metrics

    def update_from_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """One gradient step on a (device-ready) batch."""
        batch = jax.tree_util.tree_map(jnp.asarray, batch)
        self.params, self.opt_state, metrics = self._jit_update(
            self.params, self.opt_state, batch
        )
        self._num_updates += 1
        return {k: float(v) for k, v in metrics.items()}

    def compute_gradients(self, batch: Dict[str, np.ndarray]):
        """Grads without applying — used by multi-learner grad averaging."""
        batch = jax.tree_util.tree_map(jnp.asarray, batch)
        (loss, metrics), grads = jax.value_and_grad(self.loss_fn, has_aux=True)(
            self.params, batch
        )
        metrics = dict(metrics)
        metrics["total_loss"] = loss
        metrics["grad_norm"] = optax.global_norm(grads)
        return grads, {k: float(v) for k, v in metrics.items()}

    def apply_gradients(self, grads) -> None:
        updates, self.opt_state = self.optimizer.update(
            grads, self.opt_state, self.params
        )
        self.params = optax.apply_updates(self.params, updates)
        self._num_updates += 1

    # -- weights -------------------------------------------------------------

    def get_weights(self) -> Any:
        return jax.device_get(self.params)

    def set_weights(self, weights) -> None:
        self.params = jax.tree_util.tree_map(jnp.asarray, weights)

    def get_state(self) -> Dict[str, Any]:
        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
            "num_updates": self._num_updates,
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = jax.tree_util.tree_map(jnp.asarray, state["params"])
        self.opt_state = jax.tree_util.tree_map(jnp.asarray, state["opt_state"])
        self._num_updates = state.get("num_updates", 0)

    def _next_rng(self):
        self.rng, k = jax.random.split(self.rng)
        return k
