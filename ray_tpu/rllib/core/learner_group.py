"""LearnerGroup: one local learner, or a gang of learner actors.

Analog of rllib/core/learner/learner_group.py:69. TPU-first data
parallelism: each learner computes grads on its batch shard and the group
averages them (the reference wraps torch DDP instead — torch_learner.py:354).
On a real pod slice the learner gang is one actor per TPU host and the
in-actor update itself is a sharded jit program; the actor tier here handles
multi-host fan-out and fault tolerance.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional

import cloudpickle
import jax
import numpy as np

import ray_tpu
from ray_tpu.rllib.utils.actor_manager import FaultTolerantActorManager

logger = logging.getLogger(__name__)


class _LearnerActor:
    """Actor shell hosting a Learner (reference: learner actors under
    FaultTolerantActorManager, learner_group.py:178)."""

    def __init__(self, learner_blob: bytes):
        build = cloudpickle.loads(learner_blob)
        self.learner = build()

    def ping(self):
        return "pong"

    def update_from_batch(self, batch):
        return self.learner.update_from_batch(batch)

    def compute_gradients(self, batch):
        grads, metrics = self.learner.compute_gradients(batch)
        return jax.device_get(grads), metrics

    def apply_gradients(self, grads):
        self.learner.apply_gradients(grads)

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights):
        self.learner.set_weights(weights)

    def get_state(self):
        return self.learner.get_state()

    def set_state(self, state):
        self.learner.set_state(state)


def _mean_tree(trees: List[Any]):
    return jax.tree_util.tree_map(lambda *xs: sum(xs) / len(xs), *trees)


class LearnerGroup:
    def __init__(
        self,
        learner_builder: Callable[[], Any],
        *,
        num_learners: int = 0,
        num_cpus_per_learner: float = 1.0,
        num_tpus_per_learner: float = 0.0,
    ):
        self._builder = learner_builder
        self.num_learners = num_learners
        if num_learners == 0:
            self._local = learner_builder()
            self._manager = None
        else:
            self._local = None
            blob = cloudpickle.dumps(learner_builder)
            cls = ray_tpu.remote(_LearnerActor)
            actors = [
                cls.options(
                    num_cpus=num_cpus_per_learner,
                    num_tpus=num_tpus_per_learner or None,
                    max_restarts=1,
                ).remote(blob)
                for _ in range(num_learners)
            ]
            self._manager = FaultTolerantActorManager(actors)

    @property
    def is_local(self) -> bool:
        return self._local is not None

    def update_from_batch(
        self, batch: Dict[str, np.ndarray], *, time_major: bool = False
    ) -> Dict[str, float]:
        """One synchronous update. Remote mode: shard batch across healthy
        learners, average grads, apply everywhere (keeps learners in sync).
        time_major=True shards [T, B, ...] arrays along the B axis (IMPALA
        fragments must never be split along time — V-trace scans over T)."""
        if self._local is not None:
            return self._local.update_from_batch(batch)
        ids = self._manager.healthy_actor_ids()
        if not ids:
            raise RuntimeError("no healthy learner actors")
        shards = _shard_batch(batch, len(ids), time_major=time_major)
        refs = [
            (i, self._manager.actors[i].compute_gradients.remote(shard))
            for i, shard in zip(ids, shards)
        ]
        metrics_list = []
        grads_list = []
        for i, ref in refs:
            try:
                grads, metrics = ray_tpu.get(ref)
                grads_list.append(grads)
                metrics_list.append(metrics)
            except Exception as e:
                self._manager.set_actor_state(i, False)
                logger.warning("learner %d failed update: %r", i, e)
        if not grads_list:
            raise RuntimeError("all learner actors failed the update")
        mean_grads = _mean_tree(grads_list)
        self._manager.foreach_actor(
            lambda a: a.apply_gradients.remote(mean_grads)
        )
        out = {
            k: float(np.mean([m[k] for m in metrics_list]))
            for k in metrics_list[0]
        }
        return out

    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        ids = self._manager.healthy_actor_ids()
        return ray_tpu.get(self._manager.actors[ids[0]].get_weights.remote())

    def set_weights(self, weights) -> None:
        if self._local is not None:
            self._local.set_weights(weights)
        else:
            self._manager.foreach_actor(lambda a: a.set_weights.remote(weights))

    def get_state(self):
        if self._local is not None:
            return self._local.get_state()
        ids = self._manager.healthy_actor_ids()
        return ray_tpu.get(self._manager.actors[ids[0]].get_state.remote())

    def set_state(self, state) -> None:
        if self._local is not None:
            self._local.set_state(state)
        else:
            self._manager.foreach_actor(lambda a: a.set_state.remote(state))

    def shutdown(self) -> None:
        if self._manager is not None:
            for a in self._manager.actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass


def _shard_batch(
    batch: Dict[str, np.ndarray], n: int, *, time_major: bool = False
) -> List[Dict[str, np.ndarray]]:
    if n == 1:
        return [batch]
    if not time_major:
        size = len(next(iter(batch.values())))
        idx = np.array_split(np.arange(size), n)
        return [{k: v[ix] for k, v in batch.items()} for ix in idx]
    # Time-major [T, B, ...]: shard the batch axis (1); per-env vectors like
    # bootstrap_value [B] shard axis 0.
    ref = batch.get("rewards")
    if ref is None:
        ref = next(v for v in batch.values() if np.ndim(v) >= 2)
    B = np.shape(ref)[1]
    idx = np.array_split(np.arange(B), n)
    shards = []
    for ix in idx:
        shard = {}
        for k, v in batch.items():
            v = np.asarray(v)
            if v.ndim >= 2 and v.shape[1] == B:
                shard[k] = v[:, ix]
            elif v.ndim == 1 and v.shape[0] == B:
                shard[k] = v[ix]
            else:
                raise ValueError(
                    f"cannot shard key {k!r} with shape {v.shape} over batch "
                    f"axis of size {B}"
                )
        shards.append(shard)
    return shards
