"""SingleAgentEnvRunner: actor that collects experience from vector envs.

Analog of rllib/env/single_agent_env_runner.py:42 (sample:120): gymnasium
vector env stepping with jitted policy inference. Returns time-major
[T, num_envs, ...] numpy batches plus episode stats; the learner side turns
these into train batches (GAE / replay) — mirroring the reference's
EnvRunner -> ConnectorV2 -> Learner pipeline.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


class SingleAgentEnvRunner:
    """Runs on CPU workers; policy inference is jitted JAX on host."""

    def __init__(
        self,
        env_name_or_factory,
        *,
        num_envs: int = 1,
        policy_kind: str = "pi_vf",  # "pi_vf" (actor-critic) or "q" (DQN)
        module_spec_dict: Optional[Dict[str, Any]] = None,
        seed: int = 0,
        worker_index: int = 0,
        env_config: Optional[Dict[str, Any]] = None,
    ):
        import gymnasium as gym
        import jax

        self._jax = jax
        # SAME_STEP autoreset: step() at episode end returns the reset obs and
        # puts the true terminal obs in infos["final_obs"] — we patch it back
        # into next_obs so value targets see the real final state.
        autoreset = gym.vector.AutoresetMode.SAME_STEP
        if isinstance(env_name_or_factory, str):
            name = env_name_or_factory
            cfg = env_config or {}
            self.envs = gym.vector.SyncVectorEnv(
                [lambda: gym.make(name, **cfg) for _ in range(num_envs)],
                autoreset_mode=autoreset,
            )
        else:
            factory = env_name_or_factory
            cfg = env_config or {}
            self.envs = gym.vector.SyncVectorEnv(
                [lambda: factory(cfg) for _ in range(num_envs)],
                autoreset_mode=autoreset,
            )
        self.num_envs = num_envs
        self.policy_kind = policy_kind
        self.worker_index = worker_index
        self.rng = jax.random.PRNGKey(seed * 10007 + worker_index)

        from ray_tpu.rllib.core import rl_module as M

        obs_space = self.envs.single_observation_space
        act_space = self.envs.single_action_space
        self.obs_dim = int(np.prod(obs_space.shape))
        if hasattr(act_space, "n"):  # Discrete
            self.num_actions = int(act_space.n)
            self.act_dim = 0
            self.act_low = self.act_high = None
        else:  # Box (continuous, SAC)
            self.num_actions = 0
            self.act_dim = int(np.prod(act_space.shape))
            self.act_low = np.asarray(act_space.low, dtype=np.float32).reshape(-1)
            self.act_high = np.asarray(act_space.high, dtype=np.float32).reshape(-1)
            if not (
                np.all(np.isfinite(self.act_low))
                and np.all(np.isfinite(self.act_high))
                and np.allclose(-self.act_low, self.act_high)
            ):
                raise ValueError(
                    "continuous policies require a bounded symmetric Box "
                    f"action space (got low={self.act_low}, "
                    f"high={self.act_high}); wrap the env with a "
                    "RescaleAction-style wrapper"
                )
        spec_kwargs = dict(module_spec_dict or {})
        spec_kwargs.setdefault("obs_dim", self.obs_dim)
        spec_kwargs.setdefault("num_actions", self.num_actions)
        if self.act_dim:
            spec_kwargs.setdefault("act_dim", self.act_dim)
            spec_kwargs.setdefault("act_limit", float(np.max(np.abs(self.act_high))))
        self.spec = M.RLModuleSpec(**spec_kwargs)

        if policy_kind == "pi_vf":
            self.params = M.init_pi_vf(self._next_rng(), self.spec)

            def _step(params, rng, obs):
                logits, value = M.forward_pi_vf(params, obs)
                actions, logp = M.sample_actions(rng, logits)
                return actions, logp, value

            self._policy_step = jax.jit(_step)
        elif policy_kind == "q":
            self.params = M.init_q(self._next_rng(), self.spec)

            def _greedy(params, obs):
                return M.forward_q(params, obs).argmax(axis=-1)

            self._greedy = jax.jit(_greedy)
        elif policy_kind == "sac":
            self.params = M.init_sac(self._next_rng(), self.spec)
            limit = self.spec.act_limit

            def _sac_step(params, rng, obs):
                return M.sac_pi(params, obs, rng, limit)

            self._sac_step = jax.jit(_sac_step)
            # Deterministic (tanh-mean) policy for evaluation rollouts.
            self._sac_greedy = jax.jit(
                lambda params, obs: M.sac_pi_deterministic(params, obs, limit)
            )
        else:
            raise ValueError(f"unknown policy_kind {policy_kind!r}")

        self._obs, _ = self.envs.reset(seed=seed * 7919 + worker_index)
        self._episode_returns = np.zeros(num_envs)
        self._episode_lens = np.zeros(num_envs, dtype=np.int64)
        self._completed: collections.deque = collections.deque(maxlen=100)
        self._weights_version = 0

    def ping(self):
        return "pong"

    def _next_rng(self):
        self.rng, k = self._jax.random.split(self.rng)
        return k

    # -- weight sync ---------------------------------------------------------

    def set_weights(self, weights, version: int = 0) -> None:
        import jax.numpy as jnp

        self.params = self._jax.tree_util.tree_map(jnp.asarray, weights)
        self._weights_version = version

    def get_weights_version(self) -> int:
        return self._weights_version

    # -- sampling ------------------------------------------------------------

    def sample(
        self,
        num_steps: int,
        *,
        epsilon: float = 0.0,
        random_actions: bool = False,
        deterministic: bool = False,
    ) -> Dict[str, Any]:
        """Collect num_steps steps from every env. Time-major output."""
        from ray_tpu.rllib.core import rl_module as M

        T, N = num_steps, self.num_envs
        obs_buf = np.empty((T, N, self.obs_dim), dtype=np.float32)
        if self.act_dim:
            act_buf = np.empty((T, N, self.act_dim), dtype=np.float32)
        else:
            act_buf = np.empty((T, N), dtype=np.int64)
        rew_buf = np.empty((T, N), dtype=np.float32)
        # `done` = terminated only; truncation bootstraps instead of zeroing.
        term_buf = np.empty((T, N), dtype=np.bool_)
        trunc_buf = np.empty((T, N), dtype=np.bool_)
        next_obs_buf = np.empty((T, N, self.obs_dim), dtype=np.float32)
        logp_buf = np.zeros((T, N), dtype=np.float32)
        val_buf = np.zeros((T, N), dtype=np.float32)

        for t in range(T):
            obs_flat = self._obs.reshape(N, -1).astype(np.float32)
            obs_buf[t] = obs_flat
            if self.policy_kind == "pi_vf":
                actions, logp, value = self._policy_step(
                    self.params, self._next_rng(), obs_flat
                )
                actions = np.asarray(actions)
                logp_buf[t] = np.asarray(logp)
                val_buf[t] = np.asarray(value)
            elif self.policy_kind == "sac":
                if random_actions:
                    # Warmup: uniform over the Box bounds (reference SAC's
                    # initial exploration steps).
                    actions = np.random.uniform(
                        self.act_low[None, :], self.act_high[None, :],
                        size=(N, self.act_dim),
                    ).astype(np.float32)
                elif deterministic:
                    actions = np.asarray(self._sac_greedy(self.params, obs_flat))
                else:
                    acts, _ = self._sac_step(
                        self.params, self._next_rng(), obs_flat
                    )
                    actions = np.asarray(acts)
            else:
                if random_actions:
                    actions = np.random.randint(0, self.num_actions, size=N)
                else:
                    greedy = np.asarray(self._greedy(self.params, obs_flat))
                    explore = np.random.rand(N) < epsilon
                    randoms = np.random.randint(0, self.num_actions, size=N)
                    actions = np.where(explore, randoms, greedy)
            env_actions = (
                actions.reshape((N,) + self.envs.single_action_space.shape)
                if self.act_dim
                else actions
            )
            next_obs, rewards, terminated, truncated, infos = self.envs.step(env_actions)
            act_buf[t] = actions
            rew_buf[t] = rewards
            term_buf[t] = terminated
            trunc_buf[t] = truncated
            next_obs_buf[t] = next_obs.reshape(N, -1).astype(np.float32)
            # Patch true terminal observations over the autoreset obs.
            final_obs = infos.get("final_obs", infos.get("final_observation"))
            if final_obs is not None:
                for i in np.nonzero(np.logical_or(terminated, truncated))[0]:
                    if final_obs[i] is not None:
                        next_obs_buf[t, i] = np.asarray(
                            final_obs[i], dtype=np.float32
                        ).reshape(-1)

            self._episode_returns += rewards
            self._episode_lens += 1
            done = np.logical_or(terminated, truncated)
            for i in np.nonzero(done)[0]:
                self._completed.append(
                    (float(self._episode_returns[i]), int(self._episode_lens[i]))
                )
                self._episode_returns[i] = 0.0
                self._episode_lens[i] = 0
            self._obs = next_obs

        out: Dict[str, Any] = {
            "obs": obs_buf,
            "actions": act_buf,
            "rewards": rew_buf,
            "terminateds": term_buf,
            "truncateds": trunc_buf,
            "next_obs": next_obs_buf,
            "episode_stats": list(self._completed),
            "weights_version": self._weights_version,
            "env_steps": T * N,
        }
        if self.policy_kind == "pi_vf":
            out["logp"] = logp_buf
            out["values"] = val_buf
            # Bootstrap value for the obs after the last step.
            _, _, bootstrap = self._policy_step(
                self.params,
                self._next_rng(),
                self._obs.reshape(N, -1).astype(np.float32),
            )
            out["bootstrap_value"] = np.asarray(bootstrap)
            # V(final_obs) at truncation boundaries, so GAE bootstraps the
            # real terminal state instead of the autoreset obs. Sparse: one
            # batched forward over just the truncated positions.
            boundary_values = np.zeros((T, N), dtype=np.float32)
            ts, is_ = np.nonzero(trunc_buf)
            if len(ts):
                _, _, v_fin = self._policy_step(
                    self.params, self._next_rng(), next_obs_buf[ts, is_]
                )
                boundary_values[ts, is_] = np.asarray(v_fin)
            out["boundary_values"] = boundary_values
        return out

    def get_spaces(self) -> Tuple[int, int]:
        return self.obs_dim, self.num_actions

    def get_act_info(self) -> Tuple[int, float]:
        """(act_dim, act_limit) for continuous action spaces (SAC)."""
        limit = float(np.max(np.abs(self.act_high))) if self.act_dim else 0.0
        return self.act_dim, limit

    def stop(self) -> None:
        self.envs.close()
