"""EnvRunnerGroup: manages local or remote env-runner actors.

Analog of rllib/env/env_runner_group.py:66: creates N SingleAgentEnvRunner
actors under a FaultTolerantActorManager, fans out sample()/set_weights()
calls, and (optionally) replaces runners that die — sampling is stateless
beyond weights, so replacement is cheap (reference: restart_failed_env_runners).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.utils.actor_manager import FaultTolerantActorManager

logger = logging.getLogger(__name__)


class EnvRunnerGroup:
    def __init__(
        self,
        *,
        env,
        env_config: Dict[str, Any],
        num_env_runners: int,
        num_envs_per_env_runner: int,
        policy_kind: str,
        module_spec_dict: Dict[str, Any],
        seed: int,
        restart_failed: bool = True,
        sample_timeout_s: float = 60.0,
        runner_cls=None,
        extra_ctor_kwargs: Optional[Dict[str, Any]] = None,
    ):
        self._runner_cls = runner_cls or SingleAgentEnvRunner
        self._extra_kwargs = dict(extra_ctor_kwargs or {})
        self._ctor_kwargs = dict(
            env=env,
            env_config=env_config,
            num_envs_per_env_runner=num_envs_per_env_runner,
            policy_kind=policy_kind,
            module_spec_dict=module_spec_dict,
            seed=seed,
        )
        self.restart_failed = restart_failed
        self.sample_timeout_s = sample_timeout_s
        self.num_env_runners = num_env_runners
        if num_env_runners == 0:
            self._local = self._make_local(0)
            self._manager = None
        else:
            self._local = None
            actors = [self._make_remote(i) for i in range(num_env_runners)]
            self._manager = FaultTolerantActorManager(actors)

    def _make_local(self, index: int):
        k = self._ctor_kwargs
        return self._runner_cls(
            k["env"],
            num_envs=k["num_envs_per_env_runner"],
            policy_kind=k["policy_kind"],
            module_spec_dict=k["module_spec_dict"],
            seed=k["seed"],
            worker_index=index,
            env_config=k["env_config"],
            **self._extra_kwargs,
        )

    def _make_remote(self, index: int):
        k = self._ctor_kwargs
        cls = ray_tpu.remote(self._runner_cls)
        return cls.options(num_cpus=1).remote(
            k["env"],
            num_envs=k["num_envs_per_env_runner"],
            policy_kind=k["policy_kind"],
            module_spec_dict=k["module_spec_dict"],
            seed=k["seed"],
            worker_index=index,
            env_config=k["env_config"],
            **self._extra_kwargs,
        )

    @property
    def local_env_runner(self) -> Optional[SingleAgentEnvRunner]:
        return self._local

    def get_spaces(self):
        if self._local is not None:
            return self._local.get_spaces()
        ids = self._manager.healthy_actor_ids()
        return ray_tpu.get(self._manager.actors[ids[0]].get_spaces.remote())

    def get_act_info(self):
        """(act_dim, act_limit) for continuous action spaces (SAC)."""
        if self._local is not None:
            return self._local.get_act_info()
        ids = self._manager.healthy_actor_ids()
        return ray_tpu.get(self._manager.actors[ids[0]].get_act_info.remote())

    # -- sampling ------------------------------------------------------------

    def sample(self, num_steps: int, **kw) -> List[Dict[str, Any]]:
        """One sample round from every healthy runner (sync barrier)."""
        if self._local is not None:
            return [self._local.sample(num_steps, **kw)]
        self._heal()
        results = self._manager.foreach_actor(
            lambda a: a.sample.remote(num_steps, **kw),
            timeout_s=self.sample_timeout_s,
        )
        out = [r.value for r in results if r.ok]
        if not out:
            raise RuntimeError(
                "all env runners failed to sample: "
                + "; ".join(repr(r.error) for r in results)
            )
        return out

    def sample_refs(self, num_steps: int, **kw) -> List[Any]:
        """Submit sample() on every healthy runner, return (actor_idx, ref)
        pairs without blocking — the IMPALA async pipeline consumes these."""
        if self._local is not None:
            raise RuntimeError("async sampling requires num_env_runners > 0")
        self._heal()
        return [
            (i, self._manager.actors[i].sample.remote(num_steps, **kw))
            for i in self._manager.healthy_actor_ids()
        ]

    def submit_sample(self, actor_idx: int, num_steps: int, **kw):
        return self._manager.actors[actor_idx].sample.remote(num_steps, **kw)

    # -- weights -------------------------------------------------------------

    def sync_weights(self, weights, version: int = 0) -> None:
        if self._local is not None:
            self._local.set_weights(weights, version)
            return
        self._manager.foreach_actor(
            lambda a: a.set_weights.remote(weights, version)
        )

    # -- fault tolerance -----------------------------------------------------

    def _heal(self) -> None:
        if self._manager is None or not self.restart_failed:
            return
        self._manager.probe_unhealthy_actors()
        for i, healthy in enumerate(self._manager._healthy):
            if not healthy:
                logger.warning("recreating env runner %d", i)
                try:
                    self._manager.replace_actor(i, self._make_remote(i))
                except Exception as e:
                    logger.warning("recreate failed: %r", e)
                    self._manager.set_actor_state(i, False)

    def mark_unhealthy(self, actor_idx: int) -> None:
        self._manager.set_actor_state(actor_idx, False)

    def stop(self) -> None:
        if self._manager is None:
            if self._local is not None:
                self._local.stop()
            return
        for a in self._manager.actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
