"""MultiAgentEnv API (reference: rllib/env/multi_agent_env.py MultiAgentEnv).

Dict-keyed multi-agent episodes: reset/step consume and produce per-agent
dicts, with the reserved "__all__" key in terminateds/truncateds signalling
episode end for everyone. Spaces are per-agent dicts so different agents may
have different observation/action shapes (policies are grouped by shared
spaces via the policy mapping).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple


class MultiAgentEnv:
    """Subclass and implement reset/step; fill observation_spaces /
    action_spaces with gymnasium spaces keyed by agent id."""

    # agent_id -> gymnasium.Space
    observation_spaces: Dict[Any, Any] = {}
    action_spaces: Dict[Any, Any] = {}

    @property
    def agents(self):
        return sorted(self.observation_spaces.keys())

    def reset(self, *, seed: Optional[int] = None) -> Tuple[Dict, Dict]:
        """-> (obs_dict, info_dict)"""
        raise NotImplementedError

    def step(
        self, action_dict: Dict[Any, Any]
    ) -> Tuple[Dict, Dict, Dict, Dict, Dict]:
        """-> (obs, rewards, terminateds, truncateds, infos); terminateds and
        truncateds carry the "__all__" aggregate key."""
        raise NotImplementedError

    def close(self) -> None:
        pass
