"""MultiAgentEnvRunner: experience collection from a MultiAgentEnv with
per-policy modules (reference: rllib/env/multi_agent_env_runner.py +
core/rl_module/multi_rl_module.py MultiRLModule).

Agents are mapped to policies by policy_mapping_fn; each policy owns its own
pi_vf module and performs ONE batched jitted forward per step over all of
its agents (the MultiRLModule idea, jax-style: group by module, batch the
group). Sample output is a per-policy dict of single-agent-shaped time-major
batches, so the per-policy learner path (GAE, minibatch SGD) is identical to
the single-agent one — agents of a policy occupy the "env" axis.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, Optional

import numpy as np


class MultiAgentEnvRunner:
    def __init__(
        self,
        env_factory: Callable[[Dict[str, Any]], Any],
        *,
        policies,
        policy_mapping_fn: Callable[[Any], str],
        module_spec_dict: Optional[Dict[str, Any]] = None,
        seed: int = 0,
        worker_index: int = 0,
        env_config: Optional[Dict[str, Any]] = None,
        num_envs: int = 1,  # env copies stepped in lockstep (vector sampling)
        policy_kind: str = "pi_vf",
    ):
        import jax

        if policy_kind != "pi_vf":
            raise ValueError(
                "MultiAgentEnvRunner currently supports actor-critic "
                f"(pi_vf) policies only, got {policy_kind!r}"
            )
        self._jax = jax
        if isinstance(env_factory, str):
            raise ValueError(
                "multi-agent envs are passed as factory callables "
                "(config.environment(env=lambda cfg: MyMultiAgentEnv(cfg)))"
            )
        # Vectorized sampling: num_envs env copies step in lockstep; each
        # policy still performs ONE batched jitted forward per step, over
        # num_envs * n_agents rows (reference: MultiAgentEnvRunner over
        # gymnasium vector envs).
        self.num_envs = max(1, int(num_envs))
        self.envs = [env_factory(env_config or {}) for _ in range(self.num_envs)]
        self.env = self.envs[0]  # spaces/agents template
        self.worker_index = worker_index
        self.rng = jax.random.PRNGKey(seed * 10007 + worker_index + 17)

        from ray_tpu.rllib.core import rl_module as M

        self.policy_ids = list(policies)
        self.mapping = policy_mapping_fn
        self.agents = list(self.env.agents)
        # Stable per-policy agent grouping (the batch axis of each policy).
        self.agents_of: Dict[str, list] = {pid: [] for pid in self.policy_ids}
        for aid in self.agents:
            pid = self.mapping(aid)
            if pid not in self.agents_of:
                raise ValueError(
                    f"policy_mapping_fn({aid!r}) -> {pid!r} not in {self.policy_ids}"
                )
            self.agents_of[pid].append(aid)

        empty = [p for p, aids in self.agents_of.items() if not aids]
        if empty:
            raise ValueError(
                f"policies {empty} have no agents mapped to them — check "
                "policy_mapping_fn (every configured policy must own at "
                "least one agent)"
            )
        self.specs: Dict[str, Any] = {}
        self.params: Dict[str, Any] = {}
        self._policy_step: Dict[str, Any] = {}
        for pid, aids in self.agents_of.items():
            if not aids:
                continue
            spaces = [self.env.observation_spaces[a] for a in aids]
            acts = [self.env.action_spaces[a] for a in aids]
            obs_dims = {int(np.prod(s.shape)) for s in spaces}
            n_actions = {int(a.n) for a in acts}
            if len(obs_dims) != 1 or len(n_actions) != 1:
                raise ValueError(
                    f"agents of policy {pid!r} must share obs/action spaces"
                )
            spec_kwargs = dict(module_spec_dict or {})
            spec_kwargs.setdefault("obs_dim", obs_dims.pop())
            spec_kwargs.setdefault("num_actions", n_actions.pop())
            spec = M.RLModuleSpec(**spec_kwargs)
            self.specs[pid] = spec
            self.params[pid] = M.init_pi_vf(self._next_rng(), spec)

            def _step(params, rng, obs):
                logits, value = M.forward_pi_vf(params, obs)
                actions, logp = M.sample_actions(rng, logits)
                return actions, logp, value

            self._policy_step[pid] = jax.jit(_step)

        # Per-env state. Per-agent liveness: an individually-terminated
        # agent may drop out of subsequent obs dicts while the episode
        # continues; its slot then replays its last obs with zero reward and
        # terminated=True (the GAE mask zeroes any contribution).
        self._last_obs = []
        self._agent_done = []
        self._episode_return = [0.0] * self.num_envs
        self._episode_len = [0] * self.num_envs
        for e, env in enumerate(self.envs):
            obs, _ = env.reset(seed=seed * 7919 + worker_index * 101 + e)
            self._last_obs.append(dict(obs))
            self._agent_done.append({a: False for a in self.agents})
        self._completed: collections.deque = collections.deque(maxlen=100)
        self._weights_version = 0

    def ping(self):
        return "pong"

    def _next_rng(self):
        self.rng, k = self._jax.random.split(self.rng)
        return k

    # -- weight sync ---------------------------------------------------------

    def set_weights(self, weights: Dict[str, Any], version: int = 0) -> None:
        import jax.numpy as jnp

        for pid, w in weights.items():
            if pid in self.params:
                self.params[pid] = self._jax.tree_util.tree_map(jnp.asarray, w)
        self._weights_version = version

    def get_weights_version(self) -> int:
        return self._weights_version

    # -- sampling ------------------------------------------------------------

    def _obs_mat(self, pid: str) -> np.ndarray:
        """[num_envs * n_agents, obs_dim] — env-major, agent-minor rows."""
        return np.stack(
            [
                np.asarray(self._last_obs[e][a], dtype=np.float32).reshape(-1)
                for e in range(self.num_envs)
                for a in self.agents_of[pid]
            ]
        )

    def sample(self, num_steps: int, **_ignored) -> Dict[str, Any]:
        """num_steps lockstep steps of every env copy. Returns
        {"policies": {pid: batch}, ...} where each batch is
        single-agent-shaped: [T, num_envs * n_agents_of_policy, ...] —
        env copies and a policy's agents both ride the batch axis, so the
        per-policy learner path is unchanged."""
        T = num_steps
        E = self.num_envs
        pids = [p for p in self.policy_ids if self.agents_of[p]]
        buf: Dict[str, Dict[str, np.ndarray]] = {}
        for pid in pids:
            n = len(self.agents_of[pid]) * E
            d = self.specs[pid].obs_dim
            buf[pid] = {
                "obs": np.empty((T, n, d), np.float32),
                "actions": np.empty((T, n), np.int64),
                "rewards": np.empty((T, n), np.float32),
                "terminateds": np.empty((T, n), np.bool_),
                "truncateds": np.empty((T, n), np.bool_),
                "next_obs": np.empty((T, n, d), np.float32),
                "logp": np.zeros((T, n), np.float32),
                "values": np.zeros((T, n), np.float32),
                # 0.0 marks padded rows of individually-terminated agents;
                # the PPO loss drops them (GAE alone does NOT zero a padded
                # row's own delta, only its bootstrap).
                "mask": np.ones((T, n), np.float32),
            }

        env_steps = 0
        for t in range(T):
            # One batched forward per policy over ALL envs' agents.
            acts_of: Dict[str, np.ndarray] = {}
            for pid in pids:
                obs_mat = self._obs_mat(pid)
                buf[pid]["obs"][t] = obs_mat
                actions, logp, value = self._policy_step[pid](
                    self.params[pid], self._next_rng(), obs_mat
                )
                acts_of[pid] = np.asarray(actions)
                buf[pid]["actions"][t] = acts_of[pid]
                buf[pid]["logp"][t] = np.asarray(logp)
                buf[pid]["values"][t] = np.asarray(value)
            for e in range(E):
                action_dict: Dict[Any, Any] = {}
                for pid in pids:
                    na = len(self.agents_of[pid])
                    for i, aid in enumerate(self.agents_of[pid]):
                        if not self._agent_done[e][aid]:
                            action_dict[aid] = int(acts_of[pid][e * na + i])
                next_obs, rewards, terms, truncs, _infos = self.envs[e].step(
                    action_dict
                )
                env_steps += 1
                all_term = bool(terms.get("__all__", False))
                all_trunc = bool(truncs.get("__all__", False))
                for pid in pids:
                    na = len(self.agents_of[pid])
                    for i, aid in enumerate(self.agents_of[pid]):
                        s = e * na + i
                        done_before = self._agent_done[e][aid]
                        buf[pid]["mask"][t, s] = 0.0 if done_before else 1.0
                        buf[pid]["rewards"][t, s] = (
                            0.0 if done_before else float(rewards.get(aid, 0.0))
                        )
                        buf[pid]["terminateds"][t, s] = bool(
                            done_before or terms.get(aid, all_term)
                        )
                        buf[pid]["truncateds"][t, s] = bool(
                            truncs.get(aid, all_trunc)
                        )
                        buf[pid]["next_obs"][t, s] = np.asarray(
                            next_obs.get(aid, self._last_obs[e][aid]),
                            dtype=np.float32,
                        ).reshape(-1)
                self._episode_return[e] += float(sum(rewards.values()))
                self._episode_len[e] += 1
                if all_term or all_trunc:
                    self._completed.append(
                        (self._episode_return[e], self._episode_len[e])
                    )
                    self._episode_return[e], self._episode_len[e] = 0.0, 0
                    obs, _ = self.envs[e].reset()
                    self._last_obs[e] = dict(obs)
                    self._agent_done[e] = {a: False for a in self.agents}
                else:
                    for aid in self.agents:
                        if aid in next_obs:
                            self._last_obs[e][aid] = next_obs[aid]
                        if terms.get(aid) or truncs.get(aid):
                            self._agent_done[e][aid] = True

        out_policies: Dict[str, Dict[str, Any]] = {}
        for pid in pids:
            b = dict(buf[pid])
            # Bootstrap V(current obs) for the step after the batch end.
            _, _, bootstrap = self._policy_step[pid](
                self.params[pid], self._next_rng(), self._obs_mat(pid)
            )
            b["bootstrap_value"] = np.asarray(bootstrap)
            # V(next_obs) at truncation boundaries (GAE bootstraps there).
            boundary = np.zeros_like(b["values"])
            ts, is_ = np.nonzero(b["truncateds"] & ~b["terminateds"])
            if len(ts):
                _, _, v_fin = self._policy_step[pid](
                    self.params[pid], self._next_rng(), b["next_obs"][ts, is_]
                )
                boundary[ts, is_] = np.asarray(v_fin)
            b["boundary_values"] = boundary
            out_policies[pid] = b
        return {
            "policies": out_policies,
            "episode_stats": list(self._completed),
            "weights_version": self._weights_version,
            "env_steps": env_steps,
        }

    # -- introspection -------------------------------------------------------

    def get_spaces(self) -> Dict[str, Any]:
        return {
            pid: (spec.obs_dim, spec.num_actions)
            for pid, spec in self.specs.items()
        }

    def stop(self) -> None:
        for env in self.envs:
            env.close()
