"""ray_tpu.rllib: reinforcement learning on the ray_tpu runtime.

Same architecture as the reference's RLlib new API stack (rllib/algorithms,
rllib/core, rllib/env), JAX-native: RLModules are pure (init, forward)
function pairs, Learners jit the whole loss→grad→apply step (MXU-friendly on
TPU), env runners are CPU actors, and multi-learner data parallelism averages
grads across a learner gang instead of wrapping torch DDP.

    from ray_tpu.rllib import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2)
        .build_algo()
    )
    while algo.train()["episode_return_mean"] < 200:
        pass
"""

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.impala import (
    APPO,
    APPOConfig,
    IMPALA,
    IMPALAConfig,
)
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.env.multi_agent_env import MultiAgentEnv
from ray_tpu.rllib.env.multi_agent_env_runner import MultiAgentEnvRunner
from ray_tpu.rllib.algorithms.bc import BC, BCConfig
from ray_tpu.rllib.algorithms.cql import CQL, CQLConfig
from ray_tpu.rllib.algorithms.marwil import MARWIL, MARWILConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.learner_group import LearnerGroup
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup
from ray_tpu.rllib.utils.actor_manager import FaultTolerantActorManager
from ray_tpu.rllib.utils.replay_buffer import ReplayBuffer

__all__ = [
    "Algorithm",
    "AlgorithmConfig",
    "APPO",
    "APPOConfig",
    "BC",
    "BCConfig",
    "CQL",
    "CQLConfig",
    "MARWIL",
    "MARWILConfig",
    "SAC",
    "SACConfig",
    "DQN",
    "DQNConfig",
    "EnvRunnerGroup",
    "FaultTolerantActorManager",
    "IMPALA",
    "IMPALAConfig",
    "Learner",
    "LearnerGroup",
    "MultiAgentEnv",
    "MultiAgentEnvRunner",
    "PPO",
    "PPOConfig",
    "ReplayBuffer",
    "RLModuleSpec",
    "SingleAgentEnvRunner",
]
