"""FaultTolerantActorManager: fan calls out to a fleet of actors, tolerate
failures.

Analog of rllib/utils/actor_manager.py (used by LearnerGroup at
learner_group.py:178 and EnvRunnerGroup): remote calls go to healthy actors
only; an actor that raises a system error is marked unhealthy and its work
redistributed; `probe_unhealthy` brings restored actors back.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import ray_tpu
from ray_tpu._private.common import (
    ActorDiedError,
    ActorUnavailableError,
    WorkerCrashedError,
)

logger = logging.getLogger(__name__)

_SYSTEM_ERRORS = (ActorDiedError, ActorUnavailableError, WorkerCrashedError)


@dataclass
class CallResult:
    actor_index: int
    ok: bool
    value: Any = None
    error: Optional[Exception] = None

    def get(self):
        if not self.ok:
            raise self.error
        return self.value


class FaultTolerantActorManager:
    def __init__(self, actors: Sequence[Any], *, max_remote_requests_in_flight: int = 2):
        self._actors: List[Any] = list(actors)
        self._healthy: List[bool] = [True] * len(self._actors)
        self.max_in_flight = max_remote_requests_in_flight

    @property
    def actors(self) -> List[Any]:
        return self._actors

    def healthy_actor_ids(self) -> List[int]:
        return [i for i, h in enumerate(self._healthy) if h]

    def num_healthy_actors(self) -> int:
        return sum(self._healthy)

    def set_actor_state(self, idx: int, healthy: bool) -> None:
        self._healthy[idx] = healthy

    def foreach_actor(
        self,
        fn: Callable[[Any], Any],
        *,
        healthy_only: bool = True,
        remote_actor_ids: Optional[Sequence[int]] = None,
        timeout_s: Optional[float] = None,
    ) -> List[CallResult]:
        """fn maps an actor handle to an ObjectRef (e.g. lambda a:
        a.sample.remote()). Blocks for all results; failures mark the actor
        unhealthy instead of raising."""
        ids = (
            list(remote_actor_ids)
            if remote_actor_ids is not None
            else (self.healthy_actor_ids() if healthy_only else range(len(self._actors)))
        )
        refs = []
        for i in ids:
            try:
                refs.append((i, fn(self._actors[i])))
            except Exception as e:
                self._mark(i, e)
                refs.append((i, None))
        results: List[CallResult] = []
        for i, ref in refs:
            if ref is None:
                results.append(
                    CallResult(i, False, error=RuntimeError("submit failed"))
                )
                continue
            try:
                value = ray_tpu.get(ref, timeout=timeout_s)
                results.append(CallResult(i, True, value=value))
            except Exception as e:
                if isinstance(e, _SYSTEM_ERRORS):
                    self._mark(i, e)
                results.append(CallResult(i, False, error=e))
        return results

    def _mark(self, idx: int, err: Exception) -> None:
        if self._healthy[idx]:
            logger.warning("actor %d marked unhealthy: %r", idx, err)
        self._healthy[idx] = False

    def probe_unhealthy_actors(
        self, probe: Optional[Callable[[Any], Any]] = None, timeout_s: float = 5.0
    ) -> List[int]:
        """Ping unhealthy actors; ones that respond are marked healthy again
        (reference: actor_manager.py probe_unhealthy_actors)."""
        restored = []
        probe = probe or (lambda a: a.ping.remote())
        for i, h in enumerate(self._healthy):
            if h:
                continue
            try:
                ray_tpu.get(probe(self._actors[i]), timeout=timeout_s)
                self._healthy[i] = True
                restored.append(i)
            except Exception:
                pass
        return restored

    def replace_actor(self, idx: int, new_actor: Any) -> None:
        self._actors[idx] = new_actor
        self._healthy[idx] = True
