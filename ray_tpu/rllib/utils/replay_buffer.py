"""Uniform FIFO replay buffer for off-policy algorithms.

Analog of rllib/utils/replay_buffers/episode_replay_buffer.py, flattened to
transition storage (obs, action, reward, next_obs, done) in preallocated
numpy rings — O(1) add, vectorized uniform sample.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int, obs_dim: int, seed: int = 0, act_dim: int = 0):
        """act_dim 0 -> discrete int actions; >0 -> continuous float vectors
        (SAC)."""
        self.capacity = capacity
        self.act_dim = act_dim
        self.obs = np.empty((capacity, obs_dim), dtype=np.float32)
        self.next_obs = np.empty((capacity, obs_dim), dtype=np.float32)
        if act_dim:
            self.actions = np.empty((capacity, act_dim), dtype=np.float32)
        else:
            self.actions = np.empty((capacity,), dtype=np.int64)
        self.rewards = np.empty((capacity,), dtype=np.float32)
        self.dones = np.empty((capacity,), dtype=np.float32)
        self._size = 0
        self._head = 0
        self._rng = np.random.RandomState(seed)

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        """batch: time-major [T, N, ...] arrays from an EnvRunner.sample()."""
        obs = batch["obs"].reshape(-1, batch["obs"].shape[-1])
        next_obs = batch["next_obs"].reshape(-1, batch["next_obs"].shape[-1])
        if self.act_dim:
            actions = batch["actions"].reshape(-1, self.act_dim)
        else:
            actions = batch["actions"].reshape(-1)
        rewards = batch["rewards"].reshape(-1)
        dones = batch["terminateds"].reshape(-1).astype(np.float32)
        n = len(obs)
        idx = (self._head + np.arange(n)) % self.capacity
        self.obs[idx] = obs
        self.next_obs[idx] = next_obs
        self.actions[idx] = actions
        self.rewards[idx] = rewards
        self.dones[idx] = dones
        self._head = (self._head + n) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.randint(0, self._size, size=batch_size)
        return {
            "obs": self.obs[idx],
            "next_obs": self.next_obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "dones": self.dones[idx],
        }
