"""Shared offline-data helpers (reference: rllib/offline/offline_data.py):
materialization and validation used by every offline algorithm (BC, MARWIL,
CQL)."""

from __future__ import annotations

from typing import List

import numpy as np


def materialize_offline(input_) -> List[dict]:
    """Rows from a ray_tpu.data Dataset or any iterable of dicts."""
    rows = input_.take_all() if hasattr(input_, "take_all") else list(input_)
    if not rows:
        raise ValueError("offline dataset is empty")
    return rows


def validate_discrete_actions(acts: np.ndarray, num_actions: int, algo: str) -> np.ndarray:
    """int64 action indices within [0, num_actions); loud errors for
    continuous or out-of-range logged actions (silent truncation would
    train on garbage indices)."""
    if not np.issubdtype(acts.dtype, np.integer):
        if not np.allclose(acts, np.round(acts)):
            raise ValueError(
                f"{algo} requires discrete integer actions; got continuous "
                f"values (dtype {acts.dtype}) — this environment/dataset "
                "combination needs a continuous learner"
            )
        acts = np.round(acts)
    acts = acts.astype(np.int64)
    if acts.min() < 0 or acts.max() >= num_actions:
        raise ValueError(
            f"offline actions outside [0, {num_actions}): "
            f"min={acts.min()}, max={acts.max()} — dataset logged from a "
            "different action space?"
        )
    return acts
