"""ray_tpu.models: flagship model families, built mesh-first.

Each model is a pure-functional JAX module: `init(rng, cfg)` returns a param
pytree, `apply(params, batch, cfg)` the forward, and `make_train_step` a
jittable (donated, sharded) update. Parallelism is expressed as PartitionSpec
annotations against the canonical mesh axes (ray_tpu.parallel.mesh), so the
same model runs single-chip through multi-pod.
"""

from ray_tpu.models.transformer import (
    TransformerConfig,
    transformer_apply,
    transformer_init,
    transformer_loss,
    make_train_step,
    param_shardings,
)
from ray_tpu.models.resnet import ResNetConfig, resnet_apply, resnet_init

__all__ = [
    "TransformerConfig",
    "transformer_init",
    "transformer_apply",
    "transformer_loss",
    "make_train_step",
    "param_shardings",
    "ResNetConfig",
    "resnet_init",
    "resnet_apply",
]
