"""Decoder-only transformer (GPT family), designed mesh-first.

The flagship model: pre-norm decoder blocks with RoPE, grouped-query
attention, SwiGLU MLP, bf16 compute / f32 master weights. Layers are stacked
into one pytree and iterated with `lax.scan`, so compile time is O(1) in
depth and XLA pipelines the weight prefetch.

Parallelism (ray_tpu.parallel.mesh axes):
  data/fsdp — batch split; fsdp additionally shards params (ZeRO-3 style)
  tensor    — heads + mlp hidden + vocab split (Megatron layout)
  sequence  — context parallelism; attention switches to ring_attention

Capability analog of what the reference reaches only through integrations
(SURVEY §5 long-context note: reference ships no native SP); here it is
native. Reference GPT-2 fine-tune workload: BASELINE.json config #5.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.ops.flash_attention import mha
from ray_tpu.ops.fused import (
    fused_rmsnorm,
    lm_head_cross_entropy,
    softmax_cross_entropy,
)
from ray_tpu.parallel import mesh as mesh_lib
from ray_tpu.parallel.ring_attention import ring_attention


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    d_model: int = 512
    n_layers: int = 6
    n_heads: int = 8
    n_kv_heads: Optional[int] = None  # None => MHA
    d_ff: Optional[int] = None  # None => 4 * d_model (SwiGLU sized 2/3)
    max_seq_len: int = 2048
    rope_theta: float = 10_000.0
    dtype: Any = jnp.bfloat16  # compute/activation dtype
    remat: bool = False  # jax.checkpoint each block
    attention_impl: str = "auto"  # auto | pallas | xla | ring
    norm_eps: float = 1e-6
    tied_embeddings: bool = True

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def ff_dim(self) -> int:
        if self.d_ff is not None:
            return self.d_ff
        return int(8 * self.d_model / 3 + 127) // 128 * 128  # SwiGLU, 128-mult


# ------------------------------------------------------------------ params

def transformer_init(rng, cfg: TransformerConfig) -> Dict[str, Any]:
    """f32 master params. Block params are stacked on a leading layer axis."""
    k_emb, k_blk, k_out = jax.random.split(rng, 3)
    d, h, hk, dh, f = (
        cfg.d_model, cfg.n_heads, cfg.kv_heads, cfg.head_dim, cfg.ff_dim,
    )

    def dense(key, shape, fan_in):
        return jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)

    L = cfg.n_layers
    ks = jax.random.split(k_blk, 7)
    blocks = {
        "attn_norm": jnp.ones((L, d), jnp.float32),
        "wq": dense(ks[0], (L, d, h * dh), d),
        "wk": dense(ks[1], (L, d, hk * dh), d),
        "wv": dense(ks[2], (L, d, hk * dh), d),
        "wo": dense(ks[3], (L, h * dh, d), h * dh),
        "mlp_norm": jnp.ones((L, d), jnp.float32),
        "w_gate": dense(ks[4], (L, d, f), d),
        "w_up": dense(ks[5], (L, d, f), d),
        "w_down": dense(ks[6], (L, f, d), f),
    }
    params = {
        "embed": jax.random.normal(
            k_emb, (cfg.vocab_size, d), jnp.float32
        ) * 0.02,
        "blocks": blocks,
        "final_norm": jnp.ones((d,), jnp.float32),
    }
    if not cfg.tied_embeddings:
        params["unembed"] = dense(k_out, (d, cfg.vocab_size), d)
    return params


_LOGICAL_AXES = {
    "embed": ("vocab", "embed"),
    "unembed": ("embed", "vocab"),
    "final_norm": (None,),
    "blocks": {
        "attn_norm": ("layers", None),
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv"),
        "wv": ("layers", "embed", "kv"),
        "wo": ("layers", "heads", "embed"),
        "mlp_norm": ("layers", None),
        "w_gate": ("layers", "embed", "mlp"),
        "w_up": ("layers", "embed", "mlp"),
        "w_down": ("layers", "mlp", "embed"),
    },
}


def param_shardings(mesh, cfg: TransformerConfig):
    """NamedSharding pytree matching transformer_init's structure, derived
    from the logical-axis table + default_transformer_rules."""
    rules = mesh_lib.default_transformer_rules(mesh)

    def build(node):
        if isinstance(node, dict):
            return {k: build(v) for k, v in node.items()}
        return NamedSharding(mesh, rules.spec(node))

    table = dict(_LOGICAL_AXES)
    if cfg.tied_embeddings:
        table.pop("unembed", None)
    return build(table)


# ----------------------------------------------------------------- forward

def _rope(x, positions, theta: float):
    """Rotary embedding on [B, T, H, Dh] with integer positions [B, T]."""
    B, T, H, Dh = x.shape
    half = Dh // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, T, half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(x.dtype)


def _attention(q, k, v, cfg: TransformerConfig, seq_axis: Optional[str],
               seq_size: int):
    if cfg.attention_impl == "ring" and seq_axis is not None:
        # Inside shard_map over the sequence axis: exact ring attention.
        rep = cfg.n_heads // k.shape[2]
        if rep > 1:
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        return ring_attention(
            q, k, v, axis_name=seq_axis, axis_size=seq_size, causal=True
        )
    return mha(q, k, v, causal=True, impl=(
        cfg.attention_impl if cfg.attention_impl in ("pallas", "xla") else "auto"
    ))


def _block(x, blk, positions, cfg: TransformerConfig,
           seq_axis: Optional[str], seq_size: int):
    B, T, d = x.shape
    h, hk, dh = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    dt = cfg.dtype

    y = fused_rmsnorm(x, blk["attn_norm"], eps=cfg.norm_eps)
    q = (y @ blk["wq"].astype(dt)).reshape(B, T, h, dh)
    k = (y @ blk["wk"].astype(dt)).reshape(B, T, hk, dh)
    v = (y @ blk["wv"].astype(dt)).reshape(B, T, hk, dh)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    o = _attention(q, k, v, cfg, seq_axis, seq_size)
    x = x + o.reshape(B, T, h * dh) @ blk["wo"].astype(dt)

    y = fused_rmsnorm(x, blk["mlp_norm"], eps=cfg.norm_eps)
    gate = jax.nn.silu(y @ blk["w_gate"].astype(dt))
    up = y @ blk["w_up"].astype(dt)
    x = x + (gate * up) @ blk["w_down"].astype(dt)
    return x


def transformer_hidden(params, tokens, cfg: TransformerConfig,
                       positions=None, seq_axis: Optional[str] = None,
                       seq_size: int = 1):
    """Forward through the blocks: [B, T] tokens -> [B, T, d] normed hidden.

    When called under shard_map with the sequence sharded, pass seq_axis and
    positions holding GLOBAL positions so RoPE and causal masks are correct.
    """
    B, T = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    x = params["embed"].astype(cfg.dtype)[tokens]

    blk_fn = partial(_block, cfg=cfg, seq_axis=seq_axis, seq_size=seq_size)
    if cfg.remat:
        blk_fn = jax.checkpoint(blk_fn, static_argnums=())

    def scan_body(x, blk):
        return blk_fn(x, blk, positions), None

    x, _ = jax.lax.scan(scan_body, x, params["blocks"])
    return fused_rmsnorm(x, params["final_norm"], eps=cfg.norm_eps)


def _unembed(params, cfg: TransformerConfig):
    return params["embed"].T if cfg.tied_embeddings else params["unembed"]


def transformer_apply(params, tokens, cfg: TransformerConfig,
                      positions=None, seq_axis: Optional[str] = None,
                      seq_size: int = 1):
    """Forward: [B, T] int32 tokens -> [B, T, vocab] logits (f32)."""
    x = transformer_hidden(
        params, tokens, cfg, positions=positions, seq_axis=seq_axis,
        seq_size=seq_size,
    )
    return (x @ _unembed(params, cfg).astype(cfg.dtype)).astype(jnp.float32)


def transformer_loss(params, batch, cfg: TransformerConfig, **kw):
    """Next-token CE. batch: {'tokens': [B, T+1] or ('tokens','targets')}.

    Uses the chunked LM-head CE (ops/fused.py lm_head_cross_entropy): the
    [B*T, V] f32 logits are never materialized, which at GPT-2 vocab sizes
    is the difference between HBM-bound and MXU-bound training steps."""
    if "targets" in batch:
        tokens, targets = batch["tokens"], batch["targets"]
    else:
        tokens, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
    hidden = transformer_hidden(params, tokens, cfg, **kw)
    loss, _ = lm_head_cross_entropy(hidden, _unembed(params, cfg), targets)
    return loss


# -------------------------------------------------------------- train step

def make_train_step(cfg: TransformerConfig, mesh, optimizer=None):
    """Build (init_state, step) jitted over the mesh.

    state = {'params': f32 sharded, 'opt': optax state, 'step': scalar}
    step(state, batch) -> (state, metrics); params/opt donated.

    DP/FSDP/TP come from the in/out shardings (XLA inserts psum /
    all-gather / reduce-scatter over ICI); if the mesh has a 'sequence'
    axis the batch spec additionally shards T.
    """
    import optax

    if optimizer is None:
        optimizer = optax.adamw(3e-4, weight_decay=0.01)

    p_shard = param_shardings(mesh, cfg)
    names = mesh.axis_names
    batch_axes = tuple(a for a in ("data", "fsdp") if a in names) or None
    seq_ax = "sequence" if "sequence" in names else None
    tok_sharding = NamedSharding(mesh, P(batch_axes, seq_ax))
    repl = NamedSharding(mesh, P())

    def init_state(rng):
        params = transformer_init(rng, cfg)
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, s), params, p_shard
        )
        opt = optimizer.init(params)
        return {"params": params, "opt": opt,
                "step": jnp.zeros((), jnp.int32)}

    def loss_fn(params, batch):
        return transformer_loss(params, batch, cfg)

    @partial(jax.jit, donate_argnums=(0,))
    def step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state["params"], batch)
        updates, opt = optimizer.update(
            grads, state["opt"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        gnorm = optax.global_norm(grads)
        return (
            {"params": params, "opt": opt, "step": state["step"] + 1},
            {"loss": loss, "grad_norm": gnorm},
        )

    return init_state, step, {"tokens": tok_sharding, "replicated": repl,
                              "params": p_shard}


def _fwd_flops_per_token(cfg: TransformerConfig, seq_len: int):
    """(matmul fwd flops/token per layer, causal attn fwd flops/token per
    layer, lm-head fwd flops/token)."""
    d, f = cfg.d_model, cfg.ff_dim
    h, hk, dh = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    per_layer = 2 * d * (h * dh + 2 * hk * dh) + 2 * h * dh * d + 2 * 3 * d * f
    # Causal attention: token t attends to t+1 keys, so the average query
    # sees (seq_len + 1) / 2 positions; qk^T and pv each cost 2*h*dh flops
    # per (query, key) pair. The flash kernel really skips the masked-out
    # tiles, so crediting full seq_len here would overcount ~2x.
    attn = 2 * 2 * h * dh * ((seq_len + 1) / 2)
    embed = 2 * d * cfg.vocab_size
    return per_layer, attn, embed


def flops_per_token(cfg: TransformerConfig, seq_len: int) -> float:
    """USEFUL train FLOPs/token: 6ND rule + CAUSAL attention quadratic term.

    1 forward + backward at 2x forward (the PaLM / scaling-book accounting).
    Recomputation (remat, flash-backward recompute) is deliberately
    excluded — this is the numerator for useful-MFU. Use
    hardware_flops_per_token for what the chip actually executes.
    """
    per_layer, attn, embed = _fwd_flops_per_token(cfg, seq_len)
    return 3 * (cfg.n_layers * (per_layer + attn) + embed)


def hardware_flops_per_token(
    cfg: TransformerConfig, seq_len: int, remat: Optional[bool] = None
) -> float:
    """Actually-executed train FLOPs/token, including recomputation:

    - the pallas flash-attention backward recomputes the attention forward
      (recompute custom_vjp in ops/flash_attention.py): +1 attention fwd
      per layer, always;
    - per-block remat (cfg.remat) recomputes the whole block forward during
      the backward: +1 block fwd per layer.

    hardware-MFU = hardware_flops_per_token * tokens/s / peak must come out
    below 1.0 — the sanity bound useful-MFU alone cannot provide.
    """
    if remat is None:
        remat = cfg.remat
    per_layer, attn, embed = _fwd_flops_per_token(cfg, seq_len)
    fwd_layer = per_layer + attn
    extra = cfg.n_layers * attn  # flash bwd recompute
    if remat:
        extra += cfg.n_layers * fwd_layer  # block fwd recompute
    return 3 * (cfg.n_layers * fwd_layer + embed) + extra
