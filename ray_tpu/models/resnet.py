"""ResNet (v1.5) in functional JAX — the Train benchmark model family.

North-star workload: ResNet-50 images/sec (reference e2e numbers in
BASELINE.md rows 'Train ResNet e2e...', doc/source/train/benchmarks.rst).
Convs are NHWC (XLA's preferred TPU layout → MXU-tiled); batch norm carries
running stats in the state pytree; bf16 compute with f32 params/stats.

Data parallel: params replicated (or fsdp-sharded), batch split over
data/fsdp axes — handled by make_train_step-style sharding at the trainer
level (ray_tpu.train), not inside the model.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

_STAGES = {
    18: ((2, 2, 2, 2), False),
    34: ((3, 4, 6, 3), False),
    50: ((3, 4, 6, 3), True),
    101: ((3, 4, 23, 3), True),
    152: ((3, 8, 36, 3), True),
}


@dataclass(frozen=True)
class ResNetConfig:
    depth: int = 50
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    # TPU stem optimization (MLPerf-style): replace the 7x7/s2 conv on
    # [H, W, 3] — whose cin=3, stride-2 shape badly underfills the MXU —
    # with a 2x2 space-to-depth reshape to [H/2, W/2, 12] followed by a
    # 4x4/s1 conv. Same receptive field and output shape, much better MXU
    # tiling. Weight shapes differ, so it is opt-in (fresh training only).
    space_to_depth: bool = False

    @property
    def stages(self) -> Sequence[int]:
        return _STAGES[self.depth][0]

    @property
    def bottleneck(self) -> bool:
        return _STAGES[self.depth][1]


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * (
        (2.0 / fan_in) ** 0.5
    )


def _bn_init(c):
    return {
        "scale": jnp.ones((c,), jnp.float32),
        "bias": jnp.zeros((c,), jnp.float32),
        "mean": jnp.zeros((c,), jnp.float32),
        "var": jnp.ones((c,), jnp.float32),
    }


def block_layout(cfg: ResNetConfig):
    """Static per-block structure: (stride, cin, base, cout) tuples."""
    layout = []
    cin = cfg.width
    for stage, n_blocks in enumerate(cfg.stages):
        base = cfg.width * (2 ** stage)
        cout = base * (4 if cfg.bottleneck else 1)
        for b in range(n_blocks):
            stride = 2 if (stage > 0 and b == 0) else 1
            layout.append((stride, cin, base, cout))
            cin = cout
    return layout


def resnet_init(rng, cfg: ResNetConfig) -> Dict[str, Any]:
    keys = iter(jax.random.split(rng, 2048))
    params: Dict[str, Any] = {
        "stem_conv": (
            _conv_init(next(keys), 4, 4, 12, cfg.width)
            if cfg.space_to_depth
            else _conv_init(next(keys), 7, 7, 3, cfg.width)
        ),
        "stem_bn": _bn_init(cfg.width),
        "blocks": [],
    }
    for stride, cin, base, cout in block_layout(cfg):
        blk: Dict[str, Any] = {}
        if cfg.bottleneck:
            blk["conv1"] = _conv_init(next(keys), 1, 1, cin, base)
            blk["bn1"] = _bn_init(base)
            blk["conv2"] = _conv_init(next(keys), 3, 3, base, base)
            blk["bn2"] = _bn_init(base)
            blk["conv3"] = _conv_init(next(keys), 1, 1, base, cout)
            blk["bn3"] = _bn_init(cout)
        else:
            blk["conv1"] = _conv_init(next(keys), 3, 3, cin, base)
            blk["bn1"] = _bn_init(base)
            blk["conv2"] = _conv_init(next(keys), 3, 3, base, cout)
            blk["bn2"] = _bn_init(cout)
        if stride != 1 or cin != cout:
            blk["proj_conv"] = _conv_init(next(keys), 1, 1, cin, cout)
            blk["proj_bn"] = _bn_init(cout)
        params["blocks"].append(blk)
    final_c = block_layout(cfg)[-1][3]
    params["fc_w"] = jax.random.normal(
        next(keys), (final_c, cfg.num_classes), jnp.float32
    ) * (1.0 / final_c) ** 0.5
    params["fc_b"] = jnp.zeros((cfg.num_classes,), jnp.float32)
    return params


def _conv(x, w, stride=1, dtype=jnp.bfloat16):
    kh = w.shape[0]
    pad = kh // 2
    return jax.lax.conv_general_dilated(
        x.astype(dtype), w.astype(dtype),
        window_strides=(stride, stride),
        padding=[(pad, pad), (pad, pad)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _bn(x, bn, train: bool, momentum=0.9, eps=1e-5):
    """Returns (y, new_stats). In train mode uses batch stats (the psum over
    data axes happens automatically because XLA sees the full sharded batch
    under jit — stats are computed on the global batch).

    Stats accumulate in f32; the normalization itself applies in the compute
    dtype (bf16) with the per-channel affine folded to a single scale+bias —
    ResNet training is HBM-bandwidth-bound on TPU, so activation-sized f32
    intermediates are the thing to avoid."""
    if train:
        xf = x.astype(jnp.float32)
        mean = xf.mean(axis=(0, 1, 2))
        var = xf.var(axis=(0, 1, 2))
        new = {
            "scale": bn["scale"], "bias": bn["bias"],
            "mean": momentum * bn["mean"] + (1 - momentum) * mean,
            "var": momentum * bn["var"] + (1 - momentum) * var,
        }
    else:
        mean, var = bn["mean"], bn["var"]
        new = bn
    inv = jax.lax.rsqrt(var + eps)
    scale = (bn["scale"] * inv).astype(x.dtype)
    bias = (bn["bias"] - mean * bn["scale"] * inv).astype(x.dtype)
    return x * scale + bias, new


def resnet_apply(params, images, cfg: ResNetConfig, train: bool = False):
    """[B, H, W, 3] float images -> ([B, num_classes] f32 logits, new_params).

    new_params carries updated BN running stats when train=True (otherwise
    it aliases params).
    """
    dt = cfg.dtype
    new_params = {k: v for k, v in params.items() if k != "blocks"}
    if cfg.space_to_depth:
        b, h, w, c = images.shape
        x = images.reshape(b, h // 2, 2, w // 2, 2, c)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4 * c)
        # 4x4/s1 with (1, 2) padding keeps the 7x7/s2 stem's output shape.
        x = jax.lax.conv_general_dilated(
            x.astype(dt),
            params["stem_conv"].astype(dt),
            window_strides=(1, 1),
            padding=[(1, 2), (1, 2)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
    else:
        x = _conv(images, params["stem_conv"], stride=2, dtype=dt)
    x, new_params["stem_bn"] = _bn(x, params["stem_bn"], train)
    x = jax.nn.relu(x)
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
        [(0, 0), (1, 1), (1, 1), (0, 0)],
    )
    new_blocks = []
    for blk, (stride, _, _, _) in zip(params["blocks"], block_layout(cfg)):
        nblk: Dict[str, Any] = {}
        shortcut = x
        if "proj_conv" in blk:
            shortcut = _conv(x, blk["proj_conv"], stride=stride, dtype=dt)
            shortcut, nblk["proj_bn"] = _bn(shortcut, blk["proj_bn"], train)
            nblk["proj_conv"] = blk["proj_conv"]
        if cfg.bottleneck:
            y = _conv(x, blk["conv1"], 1, dt)
            y, nblk["bn1"] = _bn(y, blk["bn1"], train)
            y = jax.nn.relu(y)
            y = _conv(y, blk["conv2"], stride, dt)
            y, nblk["bn2"] = _bn(y, blk["bn2"], train)
            y = jax.nn.relu(y)
            y = _conv(y, blk["conv3"], 1, dt)
            y, nblk["bn3"] = _bn(y, blk["bn3"], train)
        else:
            y = _conv(x, blk["conv1"], stride, dt)
            y, nblk["bn1"] = _bn(y, blk["bn1"], train)
            y = jax.nn.relu(y)
            y = _conv(y, blk["conv2"], 1, dt)
            y, nblk["bn2"] = _bn(y, blk["bn2"], train)
        for k in ("conv1", "conv2", "conv3"):
            if k in blk:
                nblk[k] = blk[k]
        x = jax.nn.relu(y + shortcut)
        new_blocks.append(nblk)
    new_params["blocks"] = new_blocks
    x = x.mean(axis=(1, 2)).astype(jnp.float32)  # global average pool
    logits = x @ params["fc_w"] + params["fc_b"]
    return logits, new_params
