"""ray_tpu.chaos — deterministic seeded fault injection with convergence
invariants.

A seed fully determines a :class:`FaultSchedule` (wire-level drop / delay /
dup / reorder per RPC method pattern) and a :class:`NemesisPlan`
(process-level kill_worker / kill_raylet / restart_gcs). The runner executes
scenario workloads under a schedule, drives the cluster to quiescence, and
asserts the convergence invariants (lease-exactly-once, actors-terminal,
no-orphaned-tasks, store-settled, objects-reconstructable). Failing seeds
land in a JSONL replay corpus; rebuilding the schedule from a recorded seed
reproduces the identical fault sequence.

CLI: ``python -m ray_tpu.chaos --suite smoke --seeds 20``
(see ``--list`` for the scenario catalog, docs/chaos.md for the workflow).
"""

from ray_tpu.chaos.schedule import (
    FaultEvent,
    FaultLog,
    FaultSchedule,
    FaultSpec,
    NemesisPlan,
    stable_u64,
)
from ray_tpu.chaos.interceptors import ChaosInterceptor, install, uninstall
from ray_tpu.chaos.invariants import (
    ConvergenceTimeout,
    Violation,
    check,
    quiesce,
)
from ray_tpu.chaos.nemesis import ACTIONS, Nemesis

__all__ = [
    "ACTIONS",
    "ChaosInterceptor",
    "ConvergenceTimeout",
    "FaultEvent",
    "FaultLog",
    "FaultSchedule",
    "FaultSpec",
    "Nemesis",
    "NemesisPlan",
    "Violation",
    "check",
    "install",
    "quiesce",
    "stable_u64",
    "uninstall",
]
