"""RPC-layer fault interceptor.

Installs on :mod:`ray_tpu._private.rpc`'s process-wide send hook
(``rpc.set_send_interceptor``) and applies a :class:`FaultSchedule` to every
outbound frame from this process — GCS, raylets, and the driver core all
share the hook, so one schedule can delay control-plane calls, drop one-way
``PushChunk`` frames mid-object-transfer, duplicate a lease request, or swap
the order of adjacent matching frames, without any daemon knowing chaos is
installed.

Scope: SEND-side only. Frames arriving from out-of-process peers (worker
subprocesses) are not intercepted; in the in-process cluster harness that
still covers every raylet<->raylet, raylet<->GCS and driver->anything frame.

All methods run on the event-loop thread (every ``_send_nowait`` does).
"""

from __future__ import annotations

import logging
from fnmatch import fnmatch
from typing import Dict, List, Optional, Tuple

from ray_tpu._private import rpc
from ray_tpu.chaos.schedule import FaultEvent, FaultLog, FaultSchedule, FaultSpec

logger = logging.getLogger(__name__)

_KIND_TO_CLASS = {0: "request", 1: "reply", 2: "reply", 3: "push"}


def _frame_class(msg: list) -> str:
    """Map a frame to its fault class. Blob frames (kinds 4/5) carry a raw
    byte sidecar but classify like their control twin: a kind-4 blob with
    msgid 0 is a one-way push, with a msgid it is a request; kind 5 is a
    reply. The rpc layer materializes the sidecar before offering the frame
    here, so drop/delay/dup treat control frame + payload as ONE unit."""
    kind = msg[1]
    if kind == 4:
        return "push" if not msg[0] else "request"
    if kind == 5:
        return "reply"
    return _KIND_TO_CLASS.get(kind, "request")


class ChaosInterceptor:
    """Applies a schedule's decisions to outbound frames.

    Decision semantics per matched frame:

    - ``drop``     — the frame is consumed and never sent. For a one-way push
                     that is silent loss; for a request the caller rides its
                     timeout; for a reply the peer does.
    - ``delay t``  — the frame is sent after ``t`` seconds via the
                     interceptor-bypassing ``_send_direct`` (so the delayed
                     copy is not re-faulted).
    - ``dup``      — the frame is sent now AND once more in the same loop
                     tick (the duplicate bypasses the interceptor).
    - ``reorder``  — the frame is held; the NEXT frame matching the same spec
                     is sent first, then the held one (adjacent swap). Held
                     frames are flushed by ``flush_held`` at uninstall.
    """

    def __init__(self, schedule: FaultSchedule):
        self.schedule = schedule
        self.log = FaultLog()
        self._match_counts: Dict[str, int] = {s.name: 0 for s in schedule.specs}
        self._held: Dict[str, Tuple[rpc.Connection, list]] = {}
        self._timers: List = []

    # -- frame hook (rpc._send_nowait) --------------------------------------

    def __call__(self, conn: rpc.Connection, msg: list) -> bool:
        """Return True when the frame was consumed (rpc must not send it)."""
        try:
            method = msg[2]
            frame_class = _frame_class(msg)
        except Exception:
            return False
        if method == "LeaseBatch" and msg[1] == 3 and isinstance(msg[3], dict):
            # A lease batch is a transport envelope, not a lease op: faults
            # target the entries it carries, so dedup/cancel/exactly-once
            # invariants are exercised per lease exactly as they were when
            # each op rode its own frame.
            return self._intercept_batch(conn, msg)
        spec = self._match(method, frame_class)
        if spec is None:
            return False
        idx = self._match_counts[spec.name]
        self._match_counts[spec.name] = idx + 1
        action = self.schedule.decision(spec.name, idx)
        if action is None:
            return self._passthrough_reorder(spec, conn, msg)
        self.log.record(FaultEvent(spec.name, idx, action, method, msg[1]))
        kind = action[0]
        if kind == "drop":
            return True
        if kind == "delay":
            loop = conn._loop
            timer = loop.call_later(action[1], conn._send_direct, msg)
            self._timers.append(timer)
            return True
        if kind == "dup":
            # One extra copy, bypassing the interceptor; the original flows
            # normally (return False) so both land in the same flush.
            conn._send_direct(msg)
            return False
        if kind == "reorder":
            held = self._held.pop(spec.name, None)
            if held is not None:
                # Two holds back to back: release the older one first.
                held[0]._send_direct(held[1])
            self._held[spec.name] = (conn, msg)
            return True
        return False

    @staticmethod
    def _entry_frame(entry: list) -> list:
        """Re-expand one batch entry ``[msgid, method, payload, deadline,
        tctx]`` into the singleton request frame ``_flush_batch`` would have
        sent for it — the form delayed/duplicated/reordered copies travel
        in (the pack layer re-derives the wire TTL from the absolute
        deadline at actual send time, so a delayed entry's budget keeps
        shrinking while it is held)."""
        msgid, method, payload, deadline, tctx = entry
        frame = [msgid, 0, method, payload]
        if deadline is not None or tctx is not None:
            frame.append(deadline)
        if tctx is not None:
            frame.append(tctx)
        return frame

    def _intercept_batch(self, conn: rpc.Connection, msg: list) -> bool:
        """Apply the schedule to each LeaseBatch entry independently:
        surviving entries are repacked into the (mutated in place) batch;
        dropped ones vanish; delayed/duplicated/reordered ones leave the
        batch and travel as singleton request frames via the
        interceptor-bypassing ``_send_direct``. Consuming every entry
        consumes the whole frame."""
        entries = msg[3].get("entries") or []
        survivors: List[list] = []
        changed = False
        for entry in entries:
            emethod = entry[1]
            spec = self._match(emethod, "request")
            if spec is None:
                survivors.append(entry)
                continue
            idx = self._match_counts[spec.name]
            self._match_counts[spec.name] = idx + 1
            action = self.schedule.decision(spec.name, idx)
            if action is None:
                held = self._held.pop(spec.name, None)
                if held is not None:
                    # Adjacent swap across the batch boundary: this entry
                    # goes first (as a singleton), the held frame behind it.
                    conn._send_direct(self._entry_frame(entry))
                    held[0]._send_direct(held[1])
                    changed = True
                    continue
                survivors.append(entry)
                continue
            self.log.record(FaultEvent(spec.name, idx, action, emethod, 0))
            kind = action[0]
            if kind == "drop":
                changed = True
                continue
            if kind == "delay":
                timer = conn._loop.call_later(
                    action[1], conn._send_direct, self._entry_frame(entry)
                )
                self._timers.append(timer)
                changed = True
                continue
            if kind == "dup":
                conn._send_direct(self._entry_frame(entry))
                survivors.append(entry)
                continue
            if kind == "reorder":
                held = self._held.pop(spec.name, None)
                if held is not None:
                    held[0]._send_direct(held[1])
                self._held[spec.name] = (conn, self._entry_frame(entry))
                changed = True
                continue
            survivors.append(entry)
        if not changed:
            return False
        if not survivors:
            return True
        msg[3]["entries"] = survivors
        return False

    def _passthrough_reorder(
        self, spec: FaultSpec, conn: rpc.Connection, msg: list
    ) -> bool:
        """A non-fired match still releases a frame held by a reorder on the
        same spec — the adjacent swap: current frame first, held frame
        right behind it."""
        held = self._held.pop(spec.name, None)
        if held is None:
            return False
        conn._send_direct(msg)
        held[0]._send_direct(held[1])
        return True

    def _match(self, method: str, frame_class: str) -> Optional[FaultSpec]:
        for spec in self.schedule.specs:
            if spec.frame not in ("any", frame_class):
                continue
            if fnmatch(method, spec.method):
                return spec
        return None

    # -- lifecycle -----------------------------------------------------------

    def flush_held(self) -> None:
        """Deliver every held (reorder) frame and cancel pending delay
        timers' bookkeeping list. Called at uninstall so no frame is lost to
        schedule teardown (delay timers themselves still fire; _send_direct
        no-ops on closed connections)."""
        held, self._held = self._held, {}
        for conn, msg in held.values():
            conn._send_direct(msg)
        self._timers = [t for t in self._timers if not t.cancelled()]


def install(schedule: FaultSchedule) -> ChaosInterceptor:
    """Install a schedule process-wide. Returns the live interceptor (its
    ``log`` fills as faults fire). Loop thread only."""
    if rpc.get_send_interceptor() is not None:
        raise RuntimeError("a chaos interceptor is already installed")
    interceptor = ChaosInterceptor(schedule)
    rpc.set_send_interceptor(interceptor)
    return interceptor


def uninstall() -> Optional[ChaosInterceptor]:
    """Remove the installed interceptor (if any), flushing held frames so
    in-flight reorders complete. Loop thread only."""
    interceptor = rpc.get_send_interceptor()
    rpc.set_send_interceptor(None)
    if isinstance(interceptor, ChaosInterceptor):
        interceptor.flush_held()
        return interceptor
    return None
