"""Exhaustively-explored concurrency scenarios for the interleaving explorer.

Each scenario here is a SMALL, CLOSED protocol interaction — a handful of
concurrent tasks over real control-plane code (raylet lease ledger,
replicated store promotion, pubsub resubscribe) whose whole schedule space
``ray_tpu.devtools.explore`` can enumerate.  Unlike ``chaos.scenarios``-style
randomized soak runs, a clean report here is a PROOF over the modeled space:
every interleaving of the tasks' wakeups and timers was executed and the
invariants held in all of them.

The contract with the explorer (``explore.Explorer``):

- a spec in ``SCENARIOS`` exposes ``description`` and
  ``factory(mutations=[...]) -> scenario instance``;
- the instance exposes ``async run() -> List[str]`` returning violation
  strings (empty == invariants held on this schedule) and a synchronous
  ``cleanup()`` called after every run, pass or fail;
- ``run()`` must be deterministic given the explorer's schedule choices:
  no wall-clock reads that steer control flow, no real sockets, no
  subprocesses.  Timers are fine — the virtual loop owns the clock.

Mutations re-introduce historical bugs behind a flag so CI can prove the
explorer still has teeth: ``double_grant`` disables BOTH layers of the PR 2
duplicate-lease fix (the grant ledger and the leases[] recovery branch);
the explorer must find a schedule that corrupts the resource ledger, and
the committed trace in ``tests/schedules/`` must replay to that violation.
"""

from __future__ import annotations

import asyncio
import os
import shutil
import tempfile
from typing import Any, Callable, Dict, List, Sequence

__all__ = ["SCENARIOS", "ScenarioSpec"]


class ScenarioSpec:
    """Registry entry: a named scenario class plus its supported mutations."""

    def __init__(self, cls: Callable[..., Any], description: str):
        self.cls = cls
        self.description = description

    @property
    def mutations(self) -> Sequence[str]:
        return getattr(self.cls, "MUTATIONS", ())

    def factory(self, mutations: Sequence[str] = ()) -> Any:
        unknown = set(mutations) - set(self.mutations)
        if unknown:
            raise ValueError(
                f"unknown mutation(s) {sorted(unknown)} for this scenario; "
                f"supported: {sorted(self.mutations)}"
            )
        return self.cls(mutations=list(mutations))


class LeaseExactlyOnce:
    """Concurrent grant / duplicate-grant / cancel frames for ONE lease id.

    Three tasks race against a sim-worker raylet with CPU capacity 2: two
    requesters carrying the same lease id (a wire-duplicated
    RequestWorkerLease frame — the PR 2 incident shape) that return their
    worker once granted, and a canceller for that id.  Every interleaving
    must leave the raylet balanced: no live leases, availability restored
    to total, and ``chaos.invariants.check_leases`` clean (no worker held
    by two leases, no leaked grant).

    The ``double_grant`` mutation disables the duplicate-grant ledger AND
    the ``leases[]`` recovery branch; schedules where both grants commit
    then overwrite each other leak a worker's resources, which the final
    ledger check reports.
    """

    MUTATIONS = ("double_grant",)
    LEASE_ID = "L-explore-1"

    def __init__(self, mutations: Sequence[str] = ()):
        from ray_tpu._private import raylet as raylet_mod

        self._raylet_mod = raylet_mod
        self._mutate = "double_grant" in mutations
        self._raylet: Any = None
        if self._mutate:
            raylet_mod.Raylet._mutate_double_grant = True

    async def run(self) -> List[str]:
        from ray_tpu._private.common import ResourceSet
        from ray_tpu.chaos import invariants

        raylet = self._raylet_mod.Raylet(
            gcs_addr=("127.0.0.1", 1),
            session_name="explore",
            resources={"CPU": 2.0},
            object_store_memory=1 << 20,
            node_id="e0" * 14,
            sim_workers=True,
        )
        self._raylet = raylet
        # start() never runs under the virtual loop (it would bind sockets);
        # sim-worker handles read the listen address, so pin it by hand.
        raylet.addr = ("127.0.0.1", 0)

        payload = {
            "lease_id": self.LEASE_ID,
            "resources": ResourceSet({"CPU": 1.0}).to_units(),
            # Mark as spilled here by a peer: skips the locality/policy
            # pick (which would need a GCS view) and queues locally.
            "spilled_from": "peer-node",
        }

        async def requester() -> None:
            reply = await raylet._request_worker_lease(None, dict(payload))
            if reply.get("granted"):
                await raylet._return_worker(
                    None, {"lease_id": self.LEASE_ID}
                )

        async def canceller() -> None:
            await raylet._cancel_worker_lease(
                None, {"lease_id": self.LEASE_ID}
            )

        await asyncio.gather(requester(), requester(), canceller())

        violations = [str(v) for v in invariants.check_leases(raylet)]
        if raylet.leases:
            violations.append(
                f"lease-exactly-once: {len(raylet.leases)} lease(s) still "
                "live after every requester returned its worker"
            )
        if raylet.available != raylet.total:
            violations.append(
                "resource-ledger: availability "
                f"{raylet.available.to_dict()} != total "
                f"{raylet.total.to_dict()} after all leases released"
            )
        return violations

    def cleanup(self) -> None:
        if self._mutate:
            self._raylet_mod.Raylet._mutate_double_grant = False
        raylet = self._raylet
        self._raylet = None
        if raylet is None:
            return
        raylet._io_pool.shutdown(wait=False)
        close = getattr(raylet.store, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                pass
        shutil.rmtree(raylet.spill_dir, ignore_errors=True)


class HaPromotion:
    """Standby promotion racing a still-writing primary over a shared
    follower (the epoch-fencing protocol of the HA control plane).

    The term-1 primary streams puts while a term-2 standby adopts the
    shared follower, raises the fence, rewrites the leadership record and
    writes its own data — at every possible interleaving of the two tasks'
    event-loop ticks.  Invariants, checked per schedule:

    - no split brain: once the standby exists, a probe write on the old
      primary raises StaleLeaderError (or poisons it as fenced);
    - the standby itself is never fenced;
    - durability of acks: every term-1 key whose group commit SUCCEEDED
      (observed via ``commit_listener``, which fence aborts never fire) is
      present in the follower's final state;
    - the follower's final leadership record carries term 2.
    """

    MUTATIONS = ()
    # Number of term-1 puts streamed before the final flush.  One put keeps
    # the full space within an exhaustive CI budget; more puts multiply the
    # promotion landing points for deeper offline runs.
    PUTS = 1

    def __init__(self, mutations: Sequence[str] = ()):
        self._tmp = tempfile.mkdtemp(prefix="explore-ha-")
        self._stores: List[Any] = []

    async def run(self) -> List[str]:
        from ray_tpu._private import gcs_ha, gcs_store, rpc

        violations: List[str] = []
        follower = os.path.join(self._tmp, "shared.follower")

        primary = gcs_store.ReplicatedStoreClient(
            os.path.join(self._tmp, "a.log"),
            followers=[follower],
            term=1,
            sync="off",
        )
        self._stores.append(primary)
        gcs_ha.write_leadership(primary, 1, ("hostA", 1))

        # Ack tracking: keys move sent -> acked only when their group
        # commit ships (the listener); a fence abort drops them unacked.
        sent: List[str] = []
        acked: List[str] = []

        def on_commit(seq: int, n_ops: int) -> None:
            acked.extend(sent[:n_ops])
            del sent[:n_ops]

        primary.commit_listener = on_commit

        async def old_primary() -> None:
            try:
                for i in range(self.PUTS):
                    key = f"t1-k{i}"
                    sent.append(key)
                    primary.put("data", key, b"v1")
                    await asyncio.sleep(0)
                primary.flush()
            except rpc.StaleLeaderError:
                pass

        async def standby() -> None:
            await asyncio.sleep(0)
            # Constructor adopts the freshest member then fences term 2 on
            # every member — synchronous, so the explorer is probing WHERE
            # in the primary's write stream the promotion lands.
            promoted = gcs_store.ReplicatedStoreClient(
                os.path.join(self._tmp, "b.log"),
                followers=[follower],
                term=2,
                sync="off",
            )
            self._stores.append(promoted)
            gcs_ha.write_leadership(promoted, 2, ("hostB", 2))
            promoted.put("data", "t2-k0", b"v2")
            promoted.flush()
            if promoted.fenced:
                violations.append(
                    "ha-promotion: promoted term-2 store got fenced"
                )

        await asyncio.gather(old_primary(), standby())

        # Split-brain probe: the deposed primary must refuse new writes.
        try:
            primary.put("data", "probe", b"p")
            primary.flush()
            if not primary.fenced:
                violations.append(
                    "ha-no-split-brain: deposed term-1 primary accepted a "
                    "write after term-2 promotion"
                )
        except rpc.StaleLeaderError:
            pass

        tailer = gcs_store.ReplicaTailer(follower)
        tailer.poll()
        for key in acked:
            if tailer.get("data", key) is None:
                violations.append(
                    f"ha-ack-durability: acked term-1 key {key!r} missing "
                    "from the follower after promotion"
                )
        leadership = gcs_ha.read_leadership(tailer)
        if leadership is None or leadership.get("term") != 2:
            violations.append(
                "ha-promotion: follower leadership record is "
                f"{leadership!r}, expected term 2"
            )
        return violations

    def cleanup(self) -> None:
        for store in self._stores:
            try:
                store.close()
            except Exception:
                pass
        self._stores.clear()
        shutil.rmtree(self._tmp, ignore_errors=True)


class QuorumElection:
    """Quorum-freshest election racing a partitioned follower's rejoin.

    A 3-member group (primary + followers fA, fB) runs with fB dark behind
    a minority partition: quorum commits keep acking on primary+fA, so fB
    is a stale laggard.  Then the primary host dies (crash + drop_host —
    disk gone) and a promoter elects over the survivors [fA, fB], which is
    a 2-member group needing BOTH members reachable; while fB is still
    partitioned the election must fail closed with QuorumLostError, and
    the promoter retries until a concurrently-scheduled healer rejoins fB.
    The explorer probes every landing point of the heal against the retry
    loop.  Invariants, checked per schedule:

    - liveness under a minority partition: every writer flush acks
      (commit_listener fires) while fB is dark;
    - fail-closed: no promotion happens before fB is healed, and the
      first election attempt after the heal must succeed;
    - quorum-freshest adoption: the promoted store (elected at max
      (term, seq) over the rejoined members) contains every acked term-1
      key — stale fB must never win over fA;
    - fence bump on rejoin: after a post-election write, fB's file carries
      the promoted term and the full acked state (catch-up snapshot).
    """

    MUTATIONS = ()
    PUTS = 2
    RETRIES = 40

    def __init__(self, mutations: Sequence[str] = ()):
        self._tmp = tempfile.mkdtemp(prefix="explore-quorum-")
        self._stores: List[Any] = []

    async def run(self) -> List[str]:
        from ray_tpu._private import gcs_store

        violations: List[str] = []
        f_a = os.path.join(self._tmp, "member.fA")
        f_b = os.path.join(self._tmp, "member.fB")
        primary_path = os.path.join(self._tmp, "member.primary")

        gcs_store.partition_host(f_b)
        primary = gcs_store.ReplicatedStoreClient(
            primary_path, followers=[f_a, f_b], term=1, sync="off"
        )
        self._stores.append(primary)

        sent: List[str] = []
        acked: List[str] = []

        def on_commit(seq: int, n_ops: int) -> None:
            acked.extend(sent[:n_ops])
            del sent[:n_ops]

        primary.commit_listener = on_commit
        for i in range(self.PUTS):
            key = f"t1-k{i}"
            sent.append(key)
            primary.put("data", key, b"v1")
            primary.flush()
        if sent:
            violations.append(
                "quorum-liveness: writes did not ack under a minority "
                f"partition (unacked: {sent})"
            )
        # Host loss: the leader process dies AND its log member's disk is
        # gone. Survivors are fA (quorum-fresh) and fB (stale, still dark).
        primary.crash()
        gcs_store.drop_host(primary_path)

        promoted_box: List[Any] = []
        healed = asyncio.Event()

        async def healer() -> None:
            await asyncio.sleep(0)
            gcs_store.heal_host(f_b)
            healed.set()

        async def promoter() -> None:
            # Election attempts race the heal: an attempt landing before it
            # must fail closed (QuorumLostError), after which the promoter
            # blocks on the heal signal and the next attempt must succeed.
            # (An unconditional retry-on-sleep loop would depend on
            # scheduler fairness, which the explorer rightly violates.)
            for _ in range(self.RETRIES):
                try:
                    promoted = gcs_store.ReplicatedStoreClient(
                        f_a, followers=[f_b], term=2, sync="off"
                    )
                except gcs_store.QuorumLostError:
                    if f_b not in gcs_store.partitioned_hosts():
                        violations.append(
                            "quorum-election: QuorumLostError after the "
                            "partition healed"
                        )
                        return
                    await healed.wait()
                    continue
                if f_b in gcs_store.partitioned_hosts():
                    violations.append(
                        "quorum-election: promotion succeeded while the "
                        "2-member survivor group was missing fB"
                    )
                self._stores.append(promoted)
                promoted_box.append(promoted)
                return
            violations.append(
                "quorum-election: election kept failing after the heal"
            )

        await asyncio.gather(healer(), promoter())
        if not promoted_box:
            return violations
        promoted = promoted_box[0]

        for key in acked:
            if promoted.get("data", key) is None:
                violations.append(
                    f"quorum-freshest: acked key {key!r} missing from the "
                    "elected state (stale rejoined member won?)"
                )
        promoted.put("data", "t2-k0", b"v2")
        promoted.flush()
        promoted.wait_replication()
        if promoted.fenced:
            violations.append("quorum-election: promoted store got fenced")

        tailer = gcs_store.ReplicaTailer(f_b)
        tailer.poll()
        tables, term = tailer.tables, tailer.term
        if term != 2:
            violations.append(
                f"quorum-rejoin: fB fence/term is {term} after catch-up, "
                "expected the promoted term 2"
            )
        have = set(tables.get("data", {}).keys())
        missing = (set(acked) | {"t2-k0"}) - have
        if missing:
            violations.append(
                "quorum-rejoin: fB missing keys after catch-up snapshot: "
                f"{sorted(missing)}"
            )
        return violations

    def cleanup(self) -> None:
        from ray_tpu._private import gcs_store

        gcs_store.heal_all_partitions()
        for store in self._stores:
            try:
                store.close()
            except Exception:
                pass
        self._stores.clear()
        shutil.rmtree(self._tmp, ignore_errors=True)


class ResubscribeGap:
    """Pubsub overflow-shed / snapshot-pull gap closure, frame by frame.

    A real ``pubsub.Publisher`` and a real ``gcs.GcsClient`` talk over an
    in-memory transport pair where EVERY frame delivery is an explorer
    choice point.  The subscriber's buffer is pinned to one message, so
    publishing three versions can shed the backlog in any pattern the
    schedule allows; a shed shows up client-side as a seqno gap, which must
    trigger the Snapshot pull and still converge.  Invariants per schedule:

    - convergence: the client's last delivered version equals the
      publisher's final state and its seqno cursor catches up;
    - monotonicity: delivered versions never go backwards (a stale
      snapshot applied over a newer pub would).
    """

    MUTATIONS = ()
    CHANNEL = "explore:counter"

    def __init__(self, mutations: Sequence[str] = ()):
        from ray_tpu._private.common import config

        self._config = config
        # Buffer of ONE queued message per subscriber: any two publishes in
        # flight shed the older (instance attr; _Config.__getattr__ caches
        # computed values on the instance, so pop() restores the default).
        config.pubsub_max_buffered_msgs = 1

    async def run(self) -> List[str]:
        from ray_tpu._private import gcs, pubsub
        from ray_tpu.devtools import explore

        violations: List[str] = []
        publisher = pubsub.Publisher()
        state = {"v": 0}
        term = 1
        server_side: Dict[str, Any] = {}

        # Thin GCS façade: the Subscribe/Snapshot reply shapes of
        # gcs.GcsServer over the scenario's `state`, without the server's
        # store/node machinery.
        async def on_subscribe(conn: Any, p: dict) -> dict:
            seq = publisher.subscribe(p["channel"], server_side["conn"])
            return {
                "ok": True,
                "seq": seq,
                "pub_epoch": publisher.epoch,
                "leader_term": term,
            }

        async def on_snapshot(conn: Any, p: dict) -> dict:
            return {
                "snapshot": {"v": state["v"]},
                "seq": publisher.seqnos.get(p["channel"], 0),
                "pub_epoch": publisher.epoch,
                "leader_term": term,
            }

        client_conn, server_conn = explore.virtual_connection_pair(
            {},
            {"Subscribe": on_subscribe, "Snapshot": on_snapshot},
        )
        server_side["conn"] = server_conn
        client = gcs.GcsClient(client_conn)

        delivered: List[int] = []

        def on_msg(msg: Any) -> None:
            if isinstance(msg, dict) and "v" in msg:
                delivered.append(msg["v"])

        await client.subscribe(self.CHANNEL, on_msg)

        async def publish_stream() -> None:
            for v in (1, 2):
                state["v"] = v
                publisher.publish(self.CHANNEL, {"v": v})
                await asyncio.sleep(0)
            # Third version lands in the same tick as the second flush:
            # with a 1-message budget the drain can shed either.
            state["v"] = 3
            publisher.publish(self.CHANNEL, {"v": 3})

        await publish_stream()

        # Convergence: bounded settle loop (virtual time, so "waiting" is
        # just scheduling the remaining drain/snapshot machinery).
        for _ in range(40):
            caught_up = (
                delivered
                and delivered[-1] == state["v"]
                and client._sub_seq.get(self.CHANNEL, 0)
                >= publisher.seqnos.get(self.CHANNEL, 0)
            )
            if caught_up:
                break
            await asyncio.sleep(0.001)
        else:
            violations.append(
                "resubscribe-gap: client never converged — delivered "
                f"{delivered}, state v={state['v']}, client seq "
                f"{client._sub_seq.get(self.CHANNEL)}, publisher seq "
                f"{publisher.seqnos.get(self.CHANNEL)}"
            )

        for prev, cur in zip(delivered, delivered[1:]):
            if cur < prev:
                violations.append(
                    f"resubscribe-gap: delivered versions went backwards "
                    f"({prev} -> {cur}) in {delivered}"
                )
                break

        return violations

    def cleanup(self) -> None:
        self._config.__dict__.pop("pubsub_max_buffered_msgs", None)


SCENARIOS: Dict[str, ScenarioSpec] = {
    "lease_exactly_once": ScenarioSpec(
        LeaseExactlyOnce,
        "duplicate RequestWorkerLease frames racing a cancel against the "
        "grant ledger (mutation: double_grant re-seeds the PR 2 bug)",
    ),
    "ha_promotion": ScenarioSpec(
        HaPromotion,
        "term-2 standby promotion racing a still-writing term-1 primary "
        "over a shared follower: fencing, ack durability, leadership",
    ),
    "quorum_election": ScenarioSpec(
        QuorumElection,
        "promotion over 2 survivors racing a partitioned laggard's rejoin: "
        "fail-closed QuorumLostError, quorum-freshest adoption, fence bump",
    ),
    "resubscribe_gap": ScenarioSpec(
        ResubscribeGap,
        "pubsub overflow shedding with a 1-message buffer: seqno gap must "
        "trigger a snapshot pull and converge monotonically",
    ),
}
