"""Scenario runner + ``python -m ray_tpu.chaos`` CLI.

A *scenario* names a cluster shape, a workload, a set of fault specs, and
optional nemesis actions; a *run* executes one scenario under one seed's
:class:`FaultSchedule`, then drives the cluster to quiescence and checks the
convergence invariants plus two functional probes (old refs still ``get``
correctly — reconstruction allowed — and a fresh task still runs). Failing
seeds are appended to a JSONL replay corpus; ``--replay`` re-runs them.

Within one scenario the cluster is reused across seeds (boot cost is paid
once); any seed that fails invariants gets the cluster rebuilt so one bad
seed cannot poison the next. Scenario env overrides (chunk size, stall
timeout) are applied before cluster boot and restored after.
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import json
import os
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.chaos.schedule import FaultSchedule, FaultSpec, NemesisPlan

# -- workload helpers --------------------------------------------------------

TRANSFER_BLOB_SIZE = 300_000  # > max_direct_call_object_size -> plasma


def _blob(tag) -> bytes:
    """Deterministic payload for a tag; verified bytewise after transfer."""
    h = hashlib.sha256(repr(tag).encode()).digest()
    return (h * (TRANSFER_BLOB_SIZE // len(h) + 1))[:TRANSFER_BLOB_SIZE]


def _produce_blob(tag):
    import hashlib as _hashlib

    h = _hashlib.sha256(repr(tag).encode()).digest()
    return (h * (300_000 // len(h) + 1))[:300_000]


SPILL_BLOB_SIZE = 4 * 1024 * 1024  # a handful oversubscribe the spill arena
_SPILL_ARENA = 32 * 1024 * 1024  # per-node object store for spill scenarios


def _spill_digest(tag) -> str:
    """sha256 of the deterministic 4 MB payload for a tag (the payload bytes
    themselves; equal whether the value round-trips as bytes or uint8)."""
    h = hashlib.sha256(repr(tag).encode()).digest()
    blob = (h * (SPILL_BLOB_SIZE // len(h) + 1))[:SPILL_BLOB_SIZE]
    return hashlib.sha256(blob).hexdigest()


def _produce_spill_blob(tag):
    import hashlib as _hashlib

    import numpy as _np

    h = _hashlib.sha256(repr(tag).encode()).digest()
    n = 4 * 1024 * 1024
    # uint8 array, not bytes: numpy values are weakref-able, so the driver's
    # zero-copy value hold dies with the value and an already-pulled copy
    # stays evictable — bytes would pin the oversubscribed arena for the
    # ObjectRef's whole lifetime and wedge later pulls.
    return _np.frombuffer((h * (n // len(h) + 1))[:n], dtype=_np.uint8)


def _add(a, b):
    return a + b


class _ServeEcho:
    """Serve chaos workload: echo with a small await, so process kills land
    mid-request and delay faults have a handler window to bite."""

    async def __call__(self, x):
        await asyncio.sleep(0.02)
        return x


class _CollectiveRank:
    """Collective chaos workload: one rank of a store-backend group. The
    caller staggers contributions (delay_s) so peers are parked inside the
    group op when the nemesis kills a rank — the survivors' blocked
    allreduce must fail typed within the health deadline, never hang."""

    def __init__(self, group: str, world: int, rank: int):
        self.group, self.world, self.rank = group, world, rank

    def join(self) -> int:
        """Form the group (rendezvous actor + member registration). The
        driver gates on every rank's join before arming the nemesis: the
        scenario tests death mid-OP — a rank killed before it registers is
        unwatchable by design (nothing to watch yet)."""
        from ray_tpu.util import collective as col

        col.init_collective_group(
            self.world, self.rank, backend="store", group_name=self.group
        )
        return self.rank

    def reduce(self, delay_s: float = 0.0) -> float:
        import time as _time

        import numpy as np

        from ray_tpu.util import collective as col

        if delay_s:
            _time.sleep(delay_s)
        out = col.allreduce(
            np.full(1024, float(self.rank + 1), dtype=np.float64),
            group_name=self.group,
        )
        return float(out[0])


# -- scenario catalog --------------------------------------------------------


@dataclass
class Scenario:
    name: str
    description: str
    specs: List[FaultSpec]
    workload: str  # "tasks" | "transfer" | "serve" | "sched" | "collective" | "spill"
    steps: int = 3
    nemesis: List[str] = field(default_factory=list)
    remote_node: bool = False  # add a {"victim": 2} node for cross-node work
    env: Dict[str, str] = field(default_factory=dict)
    # Shrink each node's arena (spill workload: working set is sized as a
    # multiple of this, so pressure spilling is guaranteed, not incidental).
    object_store_memory: Optional[int] = None
    # Re-add a victim node at the end of a seed run if nemesis removed one.
    repair: bool = False
    # sched workload: size of the SimCluster (in-process raylets, no driver).
    sim_nodes: int = 0
    # sched workload: boot the SimCluster's GCS with a durable store (a
    # session tempdir) so crash_gcs has acknowledged state to recover.
    persist: bool = False
    # sched workload: replicated store + warm standby + leader file, so the
    # kill_gcs_host nemesis has a follower log to fail over onto.
    ha: bool = False
    # serve workload: per-request budget, and whether to tear down the
    # process-wide router between steps (it must rebuild from the controller).
    serve_timeout_s: float = 2.0
    router_restart: bool = False


_TRANSFER_ENV = {
    # Small chunks so one blob is many PushChunk frames; quick stall
    # detection so dropped tails re-request within the step, not after 30s.
    "RAY_TPU_OBJECT_CHUNK_SIZE": "32768",
    "RAY_TPU_PULL_STALL_TIMEOUT_S": "1.0",
    "RAY_TPU_WORKER_LEASE_IDLE_KEEP_S": "0.2",
}

_TASKS_ENV = {"RAY_TPU_WORKER_LEASE_IDLE_KEEP_S": "0.2"}

_SPILL_ENV = {
    # Spill decisions must land within a step, and a pull whose source died
    # mid-transfer re-requests quickly instead of riding out the default
    # stall window.
    "RAY_TPU_OBJECT_SPILLING_POLL_INTERVAL_S": "0.05",
    "RAY_TPU_PULL_STALL_TIMEOUT_S": "1.0",
    "RAY_TPU_WORKER_LEASE_IDLE_KEEP_S": "0.2",
}

_LATENCY_ENV = {
    # Per-attempt cap on the retryable GCS channel: a dropped reply is
    # re-issued after 2s instead of hanging the caller's whole budget.
    # Safe here because the latency workloads are tasks-only (no
    # CreateActor wait_alive long-polls ride the GCS channel).
    "RAY_TPU_RPC_DEFAULT_TIMEOUT_S": "2.0",
    "RAY_TPU_WORKER_LEASE_IDLE_KEEP_S": "0.2",
}


SCENARIOS: Dict[str, Scenario] = {
    s.name: s
    for s in [
        Scenario(
            name="rpc_delay",
            description="control-plane latency: lease requests and object "
            "lookups delayed 5-40ms",
            specs=[
                FaultSpec("delay-lease", "delay", "RequestWorkerLease",
                          frame="request", p=0.7, delay_s=(0.005, 0.04)),
                FaultSpec("delay-objget", "delay", "ObjGet",
                          frame="reply", p=0.5, delay_s=(0.005, 0.04)),
            ],
            workload="tasks",
            env=dict(_TASKS_ENV),
        ),
        Scenario(
            name="dup_lease",
            description="wire-level duplication of RequestWorkerLease frames "
            "(the raylet.leases write-write reproducer)",
            specs=[
                FaultSpec("dup-lease", "dup", "RequestWorkerLease",
                          frame="request", p=1.0, max_fires=3),
            ],
            workload="tasks",
            env=dict(_TASKS_ENV),
        ),
        Scenario(
            name="chunk_loss",
            description="one-way PushChunk loss mid object transfer; pulls "
            "must stall-detect and re-request",
            specs=[
                FaultSpec("lose-chunks", "drop", "PushChunk",
                          frame="push", p=0.25),
            ],
            workload="transfer",
            remote_node=True,
            env=dict(_TRANSFER_ENV),
        ),
        Scenario(
            name="reorder_push",
            description="adjacent PushChunk reordering; destination aborts "
            "the corrupt assembly and the pull recovers",
            specs=[
                FaultSpec("swap-chunks", "reorder", "PushChunk",
                          frame="push", p=0.15),
            ],
            workload="transfer",
            remote_node=True,
            env=dict(_TRANSFER_ENV),
        ),
        Scenario(
            name="kill_worker",
            description="SIGKILL a live worker between steps; tasks retry on "
            "a fresh lease",
            specs=[],
            workload="tasks",
            steps=4,
            nemesis=["kill_worker"],
            env=dict(_TASKS_ENV),
        ),
        Scenario(
            name="gcs_restart",
            description="kill + restart the GCS mid-workload; raylets "
            "re-register and work resumes",
            specs=[],
            workload="tasks",
            steps=4,
            nemesis=["restart_gcs"],
            env=dict(_TASKS_ENV),
        ),
        Scenario(
            name="latency_storm",
            description="heavy ambient latency: every request and reply "
            "delayed 5-80ms with p=0.35; deadlines must shrink hop to hop "
            "and no handler may outlive its caller",
            specs=[
                FaultSpec("delay-req", "delay", "*",
                          frame="request", p=0.35, delay_s=(0.005, 0.08)),
                FaultSpec("delay-rep", "delay", "*",
                          frame="reply", p=0.35, delay_s=(0.005, 0.08)),
            ],
            workload="tasks",
            env=dict(_LATENCY_ENV),
        ),
        Scenario(
            name="latency_gcs_drop",
            description="GCS reply loss: idempotent control-plane replies "
            "dropped; the retryable channel re-issues within its budget "
            "(named methods only — blanket drops would hang long-polls)",
            specs=[
                FaultSpec("drop-kv", "drop", "KV*",
                          frame="reply", p=0.2),
                FaultSpec("drop-resources", "drop", "UpdateResources",
                          frame="reply", p=0.3),
                FaultSpec("drop-nodes", "drop", "GetAllNodes",
                          frame="reply", p=0.3),
            ],
            workload="tasks",
            env=dict(_LATENCY_ENV),
        ),
        Scenario(
            name="latency_gcs_restart",
            description="ambient request latency plus a GCS kill+restart: "
            "GCS-bound calls queue across the failover and drain after "
            "reconnect as latency blips, not errors",
            specs=[
                FaultSpec("delay-req", "delay", "*",
                          frame="request", p=0.25, delay_s=(0.005, 0.06)),
            ],
            workload="tasks",
            steps=4,
            nemesis=["restart_gcs"],
            env=dict(_LATENCY_ENV),
        ),
        Scenario(
            name="serve_replica_kill",
            description="SIGKILL a serve replica worker while 16 requests "
            "are in flight; failures surface typed, the health loop replaces "
            "the replica, and fresh requests route around the corpse",
            specs=[],
            workload="serve",
            steps=4,
            nemesis=["kill_replica"],
            env=dict(_TASKS_ENV),
        ),
        Scenario(
            name="serve_deadline_storm",
            description="delay serve data-plane dispatch 10-120ms against a "
            "tight 0.4s request budget; excess latency must come back as "
            "typed sheds or deadline cuts, never an admitted request "
            "outliving its deadline",
            specs=[
                FaultSpec("delay-dispatch", "delay", "PushActorTask",
                          frame="request", p=0.5, delay_s=(0.01, 0.12)),
                FaultSpec("delay-dispatch-rep", "delay", "PushActorTask",
                          frame="reply", p=0.5, delay_s=(0.01, 0.12)),
            ],
            workload="serve",
            steps=4,
            serve_timeout_s=0.4,
            env=dict(_TASKS_ENV),
        ),
        Scenario(
            name="serve_router_restart",
            description="tear down the process-wide router between steps; a "
            "fresh router rebuilds its replica view from the controller and "
            "requests keep succeeding",
            specs=[],
            workload="serve",
            steps=4,
            router_restart=True,
            env=dict(_TASKS_ENV),
        ),
        Scenario(
            name="collective_rank_kill",
            description="SIGKILL a collective-group rank while its peers "
            "are parked inside a store-backend allreduce; survivors must "
            "fail with a typed CollectiveGroupDiedError within the health "
            "deadline — never hang — and the cluster keeps running fresh "
            "work",
            specs=[],
            workload="collective",
            steps=3,
            nemesis=["kill_collective_rank"],
            env=dict(
                _TASKS_ENV,
                RAY_TPU_COLLECTIVE_HEALTH_INTERVAL_S="0.25",
                RAY_TPU_COLLECTIVE_TIMEOUT_S="20",
            ),
        ),
        Scenario(
            name="kill_raylet",
            description="kill the node holding transferred objects; refs "
            "recover via lineage reconstruction",
            specs=[],
            workload="transfer",
            steps=3,
            nemesis=["kill_raylet"],
            remote_node=True,
            repair=True,
            env=dict(_TRANSFER_ENV),
        ),
        Scenario(
            name="spill_kill_raylet",
            description="working set 4x the arena forces pressure spilling "
            "on the victim node, then the node dies (its spill files die "
            "with it); every acknowledged object must come back bytewise "
            "intact via restore or lineage re-execution, or fail with the "
            "typed reconstruction error — never wrong bytes or a hang",
            specs=[],
            workload="spill",
            steps=3,
            nemesis=["kill_raylet"],
            remote_node=True,
            repair=True,
            env=dict(_SPILL_ENV),
            object_store_memory=_SPILL_ARENA,
        ),
        Scenario(
            name="spill_kill_worker",
            description="working set 4x the arena with a worker SIGKILLed "
            "between steps: producers retry on fresh leases while the "
            "pressure loop keeps spilling, and no acknowledged object is "
            "lost or corrupted",
            specs=[],
            workload="spill",
            steps=3,
            nemesis=["kill_worker"],
            remote_node=True,
            env=dict(_SPILL_ENV),
            object_store_memory=_SPILL_ARENA,
        ),
        Scenario(
            name="recovery_durable",
            description="hard-crash the GCS (no checkpoint, torn WAL tail) "
            "mid-workload; recovery truncates the torn frame, reloads every "
            "acknowledged record losslessly, and reconciliation re-drives "
            "in-flight creations",
            specs=[],
            workload="tasks",
            steps=4,
            nemesis=["crash_gcs"],
            env=dict(_TASKS_ENV),
        ),
        Scenario(
            name="recovery_durable_sim",
            description="200-node simulated cluster: crash the persistent "
            "GCS (torn WAL) under concurrent lease storms; restored state "
            "must be lossless and the 200-raylet reconnect wave must "
            "re-register without melting the control plane",
            specs=[],
            workload="sched",
            steps=3,
            nemesis=["crash_gcs"],
            sim_nodes=200,
            persist=True,
        ),
        Scenario(
            name="kill_gcs_host",
            description="lose the whole GCS machine mid-workload (process "
            "killed hard, its replicated-log member gone with the disk); "
            "the warm standby promotes over the surviving follower log, "
            "clients re-target via the leader file, and every acknowledged "
            "record survives — zero state loss, no split-brain",
            specs=[],
            workload="tasks",
            steps=4,
            nemesis=["kill_gcs_host"],
            env=dict(
                _TASKS_ENV,
                RAY_TPU_GCS_PERSIST_BACKEND="replicated",
                # Fast lease turnover so promotion lands inside the seed,
                # not after a 2s production lease + grace window.
                RAY_TPU_GCS_LEADER_LEASE_S="1.0",
                RAY_TPU_GCS_STANDBY_POLL_S="0.05",
            ),
        ),
        Scenario(
            name="kill_gcs_host_sim",
            description="200-node simulated cluster: kill the GCS host "
            "under concurrent lease storms; the standby promotes from the "
            "follower log and the 200-raylet reconnect wave re-targets the "
            "new leader through the leader file without melting it",
            specs=[],
            workload="sched",
            steps=3,
            nemesis=["kill_gcs_host"],
            sim_nodes=200,
            persist=True,
            ha=True,
            env={
                "RAY_TPU_GCS_LEADER_LEASE_S": "1.0",
                "RAY_TPU_GCS_STANDBY_POLL_S": "0.05",
            },
        ),
        Scenario(
            name="partition_follower",
            description="partition one follower of the 3-member replication "
            "group mid-workload, then heal: commits must keep acking on the "
            "remaining majority (the leader never stalls or demotes), and "
            "the healed member catches back up via a snapshot frame with "
            "zero acknowledged loss",
            specs=[],
            workload="tasks",
            steps=4,
            nemesis=["partition_follower", "heal_partition"],
            env=dict(
                _TASKS_ENV,
                RAY_TPU_GCS_PERSIST_BACKEND="replicated",
                RAY_TPU_GCS_LEADER_LEASE_S="1.0",
                RAY_TPU_GCS_STANDBY_POLL_S="0.05",
            ),
        ),
        Scenario(
            name="partition_majority",
            description="partition every follower away from the leader: the "
            "next group commit cannot reach a majority, so the leader must "
            "demote itself (typed StaleLeaderError, no unreplicated acks); "
            "after the heal the standby promotes at a higher term and every "
            "record acknowledged before the partition survives",
            specs=[],
            workload="tasks",
            steps=4,
            nemesis=["partition_majority"],
            env=dict(
                _TASKS_ENV,
                RAY_TPU_GCS_PERSIST_BACKEND="replicated",
                RAY_TPU_GCS_LEADER_LEASE_S="1.0",
                RAY_TPU_GCS_STANDBY_POLL_S="0.05",
            ),
        ),
        Scenario(
            name="sched_storm",
            description="120-node simulated cluster saturated with "
            "concurrent lease bursts; raylets killed mid-spillback-chain, "
            "clients re-anchor around the corpses, every surviving lease "
            "ledger must balance exactly-once",
            specs=[],
            workload="sched",
            steps=3,
            nemesis=["kill_raylet", "kill_raylet"],
            sim_nodes=120,
            repair=True,
        ),
    ]
}

SUITES: Dict[str, List[str]] = {
    # Interceptor-only faults: fast, no process churn — the CI 20-seed gate.
    "smoke": ["rpc_delay", "dup_lease", "chunk_loss", "reorder_push"],
    # Process-level nemesis: heavier, run over fewer seeds.
    "recovery": ["kill_worker", "gcs_restart", "kill_raylet"],
    # Crash-consistency: hard GCS crashes (torn WAL) with the no-state-loss
    # invariant, on a driver cluster and a 200-node sim reconnect storm —
    # plus whole-host GCS loss with warm-standby failover (HA).
    "recovery_durable": [
        "recovery_durable", "recovery_durable_sim",
        "kill_gcs_host", "kill_gcs_host_sim",
    ],
    # HA failover + replication-group partitions: the chaos-ha CI job's
    # 10+-seed gate (minority partition must not stall commits; majority
    # partition must demote the leader, then fail over on heal).
    "ha": [
        "kill_gcs_host", "kill_gcs_host_sim",
        "partition_follower", "partition_majority",
    ],
    # Delay/drop-heavy schedules exercising the RPC resilience layer
    # (retryable channels, deadline propagation, GCS failover queueing).
    "latency": ["latency_storm", "latency_gcs_drop", "latency_gcs_restart"],
    # Serving stack under fire: replica death mid-request, deadline storms,
    # router restarts — the no-request-lost-or-overrun invariant suite.
    "serve": [
        "serve_replica_kill", "serve_deadline_storm", "serve_router_restart",
    ],
    # Object plane under memory pressure: oversubscribed working sets with
    # node/worker kills — the check_no_data_loss invariant suite (the
    # chaos-spill CI job's 10-seed gate).
    "spill": ["spill_kill_raylet", "spill_kill_worker"],
    # Simulated-cluster scheduler scenarios: no driver, hundreds of
    # in-process raylets (see _private/sim_cluster.py).
    "sched": ["sched_storm"],
    # Collective groups under fire: rank death mid-allreduce must surface
    # as a typed CollectiveGroupDiedError, never a hang (docs/collectives.md).
    "collective": ["collective_rank_kill"],
    "full": [
        "rpc_delay", "dup_lease", "chunk_loss", "reorder_push",
        "latency_storm", "latency_gcs_drop", "latency_gcs_restart",
        "serve_replica_kill", "serve_deadline_storm", "serve_router_restart",
        "kill_worker", "gcs_restart", "kill_raylet", "sched_storm",
        "spill_kill_raylet", "spill_kill_worker",
        "recovery_durable", "recovery_durable_sim", "collective_rank_kill",
        "kill_gcs_host", "kill_gcs_host_sim",
        "partition_follower", "partition_majority",
    ],
}


# -- seed result -------------------------------------------------------------


@dataclass
class SeedResult:
    scenario: str
    seed: int
    ok: bool
    schedule_digest: str
    fault_log_digest: str
    faults_fired: int
    violations: List[str]
    duplicate_grants_avoided: int = 0
    stalled_streams: int = 0
    rerequested_streams: int = 0
    deadline_shed: int = 0
    deadline_enforced: int = 0
    spilled_bytes: int = 0

    def to_wire(self) -> dict:
        return {
            "scenario": self.scenario,
            "seed": self.seed,
            "ok": self.ok,
            "schedule_digest": self.schedule_digest,
            "fault_log_digest": self.fault_log_digest,
            "faults_fired": self.faults_fired,
            "violations": self.violations,
            "duplicate_grants_avoided": self.duplicate_grants_avoided,
            "stalled_streams": self.stalled_streams,
            "rerequested_streams": self.rerequested_streams,
            "deadline_shed": self.deadline_shed,
            "deadline_enforced": self.deadline_enforced,
            "spilled_bytes": self.spilled_bytes,
        }


# -- cluster/session plumbing ------------------------------------------------


class _Session:
    """One scenario's cluster + driver connection, reusable across seeds."""

    def __init__(self, scenario: Scenario):
        self.scenario = scenario
        self._saved_env: Dict[str, Optional[str]] = {}
        for k, v in scenario.env.items():
            self._saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        from ray_tpu.cluster_utils import Cluster

        head_args = {"num_cpus": 2, "num_tpus": 0}
        if scenario.object_store_memory:
            head_args["object_store_memory"] = scenario.object_store_memory
        self.cluster = Cluster(head_node_args=head_args)
        if scenario.remote_node:
            self.cluster.add_node(
                num_cpus=2,
                resources={"victim": 2},
                object_store_memory=scenario.object_store_memory,
            )
        self.cluster.connect()
        import ray_tpu
        from ray_tpu._private import worker as worker_mod

        self.ray = ray_tpu
        self.w = worker_mod.global_worker
        self.add = ray_tpu.remote(max_retries=3)(_add)
        self.produce = ray_tpu.remote(
            max_retries=3, resources={"victim": 1} if scenario.remote_node else None
        )(_produce_blob)
        self.produce_spill = ray_tpu.remote(
            max_retries=3, resources={"victim": 1} if scenario.remote_node else None
        )(_produce_spill_blob)
        self.serve = None
        self.serve_dep: Optional[str] = None
        if scenario.workload == "serve":
            from ray_tpu import serve

            self.serve = serve
            serve.start(http_options={"enabled": False})
            echo = serve.deployment(
                num_replicas=2,
                max_ongoing_requests=4,
                max_queued_requests=32,
                # Fast death detection: a killed replica must be replaced
                # within the seed, not after a 10s default health period.
                health_check_period_s=0.25,
                health_check_timeout_s=2.0,
                graceful_shutdown_timeout_s=1.0,
            )(_ServeEcho)
            serve.run(echo.bind(), route_prefix=None)
            self.serve_dep = f"default#{echo.name}"

    def run_async(self, coro, timeout=60):
        return self.w.run_async(coro, timeout=timeout)

    def repair_victim_node(self) -> None:
        have_victim = any(
            "victim" in r.total.to_dict() for r in self.cluster.raylets.values()
        )
        if not have_victim:
            self.cluster.add_node(
                num_cpus=2,
                resources={"victim": 2},
                object_store_memory=self.scenario.object_store_memory,
            )

    def close(self) -> None:
        try:
            if self.serve is not None:
                # Also clears the cached controller handle and the
                # process-wide router — both would otherwise point into this
                # (about to die) cluster when the next session boots.
                try:
                    self.serve.shutdown()
                except Exception:
                    pass
            self.cluster.shutdown()
        finally:
            for k, old in self._saved_env.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old


# -- the seed loop -----------------------------------------------------------


def run_seed(session: _Session, scenario: Scenario, seed: int,
             verbose: bool = False) -> SeedResult:
    from ray_tpu._private import rpc, telemetry
    from ray_tpu.chaos import interceptors, invariants
    from ray_tpu.chaos.nemesis import Nemesis

    schedule = FaultSchedule(seed, scenario.specs)
    plan = NemesisPlan(seed, scenario.nemesis, scenario.steps)
    nemesis = Nemesis(session.cluster)
    violations: List[str] = []
    probe_refs = []  # (ref, expected_digest)
    # spill workload: every acknowledged object as (ref, digest, kind) — the
    # check_no_data_loss invariant re-resolves all of them post-quiesce.
    data_ledger = []
    spill_seen = 0

    async def _install():
        # Start from a drained cluster (the previous seed's probe lease may
        # still be warm): every seed then re-requests leases and re-transfers
        # objects, so its schedule actually sees traffic to fault.
        await invariants.quiesce(session.cluster, timeout=15.0)
        # A previous seed's unhealed replication partition must not leak
        # into this one (partition_* nemesis actions are module-global).
        from ray_tpu._private.gcs_store import heal_all_partitions

        heal_all_partitions()
        # Per-seed deadline accounting: the no-call-outlives-deadline
        # invariant reads these process-wide counters — and the GCS-side
        # aggregate of worker-subprocess flushes — at convergence.
        rpc.deadline_stats.reset()
        gcs = session.cluster.gcs_server
        if gcs is not None:
            gcs.worker_deadline_stats.update(met=0, shed=0, enforced=0)
            gcs.worker_deadline_stats["overruns"].clear()
        # Per-seed telemetry: zero the registry and both flight-recorder
        # rings so a violation's dump holds THIS seed's timeline only.
        telemetry.reset_all()
        from ray_tpu.util import tracing

        tracing.reset()
        if gcs is not None:
            gcs.telemetry = telemetry.new_aggregate()
            gcs.flight_events.clear()
            gcs.spans.clear()
        return interceptors.install(schedule)

    async def _uninstall():
        return interceptors.uninstall()

    async def _serve_step(step, actions):
        """One burst of 16 concurrent serve requests; nemesis actions fire
        WHILE the burst is in flight (replica kill mid-request). Returns
        (outcome counters, violations, nemesis descriptions)."""
        from ray_tpu.serve import handle as handle_mod
        from ray_tpu.serve._private.common import DeploymentOverloadedError

        router = await handle_mod._get_router()
        outcomes = {"ok": 0, "shed": 0, "deadline": 0, "replica_error": 0}
        bad: List[str] = []
        error_samples: List[str] = []

        async def one(i):
            want = seed * 1000 + step * 100 + i
            try:
                got = await router.assign_request(
                    session.serve_dep,
                    {"call_method": "__call__", "request_id": "",
                     "multiplexed_model_id": ""},
                    (want,),
                    {},
                    timeout_s=scenario.serve_timeout_s,
                )
            except DeploymentOverloadedError:
                outcomes["shed"] += 1
            except (rpc.DeadlineExceeded, TimeoutError, asyncio.TimeoutError):
                outcomes["deadline"] += 1
            except Exception as e:
                # A replica killed mid-request surfaces as a typed
                # actor-death error: acceptable (callers can retry), unlike
                # a wrong value or a hang.
                outcomes["replica_error"] += 1
                if len(error_samples) < 3:
                    error_samples.append(f"{type(e).__name__}: {e}")
            else:
                if got != want:
                    bad.append(f"request {i} returned {got!r}, want {want}")
                else:
                    outcomes["ok"] += 1

        burst = asyncio.gather(*(one(i) for i in range(16)))
        fired = []
        if actions:
            await asyncio.sleep(0.02)  # let requests reach the replicas
            for action, pick in actions:
                desc = await nemesis.fire(action, pick)
                if desc:
                    fired.append(desc)
        await burst
        if not outcomes["ok"]:
            # Zero successes is about to be a violation: capture the
            # router's replica view so the corpus says *why* (stale set,
            # empty set, all corpses) instead of just the outcome counts.
            rs = router._replica_set(session.serve_dep)
            error_samples.append(
                f"replicas={[r.replica_id_str[-8:] for r in rs.replicas]} "
                f"stats={router.stats().get(session.serve_dep)}"
            )
        return outcomes, bad, fired, error_samples

    def _collective_step(step, actions):
        """One store-backend allreduce across fresh rank actors; nemesis
        kills fire WHILE the ranks are parked inside the op (rank 1's
        contribution is staggered, so every peer is blocked when the
        SIGKILL lands). Runs sync on the driver thread: the blocking is in
        the rank actors, not here. Returns (violations, fired)."""
        import time as _time

        import ray_tpu
        from ray_tpu.util.collective import CollectiveGroupDiedError

        group = f"chaos_{seed}_{step}"
        world = 2
        # Fractional CPUs: the chaos head node has 2; both ranks plus the
        # 0.1-CPU rendezvous actor must fit or rank 1 never places and the
        # group op times out without any fault having fired.
        Rank = ray_tpu.remote(max_restarts=0, num_cpus=0.5)(_CollectiveRank)
        ranks = [
            Rank.options(
                name=f"COLLECTIVE_RANK::{group}_{r}"
            ).remote(group, world, r)
            for r in range(world)
        ]
        bad: List[str] = []
        try:
            # Barrier: the group must be fully formed (store actor up, every
            # member registered) before the nemesis arms — the invariant
            # under test is death MID-OP, not death during bootstrap.
            session.ray.get([a.join.remote() for a in ranks], timeout=60)
        except Exception as e:
            bad.append(
                f"step {step}: group bootstrap failed before any fault: "
                f"{type(e).__name__}: {e}"
            )
            for a in ranks:
                try:
                    session.ray.kill(a)
                except Exception:
                    pass
            return bad, []
        refs = [ranks[0].reduce.remote(0.0), ranks[1].reduce.remote(1.5)]
        _time.sleep(0.5)  # rank 0 is parked inside the allreduce now
        fired = []
        for action, pick in actions:
            async def _fire(action=action, pick=pick):
                return await nemesis.fire(action, pick)

            desc = session.run_async(_fire(), timeout=60)
            if desc:
                fired.append(desc)
        outcomes = {"ok": 0, "typed_death": 0, "victim_died": 0}
        deadline = 30.0
        for r, ref in enumerate(refs):
            t0 = _time.monotonic()
            try:
                got = session.ray.get(ref, timeout=deadline)
            except CollectiveGroupDiedError:
                # The survivor's op failed typed — the invariant under test.
                outcomes["typed_death"] += 1
            except (
                ray_tpu.ActorDiedError,
                ray_tpu.ActorUnavailableError,
                ray_tpu.WorkerCrashedError,
            ):
                outcomes["victim_died"] += 1  # the killed rank's own call
            except ray_tpu.GetTimeoutError:
                bad.append(
                    f"step {step} rank {r}: collective op hung past "
                    f"{deadline:.0f}s (after {_time.monotonic() - t0:.1f}s) "
                    "instead of failing typed"
                )
            except Exception as e:
                bad.append(
                    f"step {step} rank {r}: untyped collective failure "
                    f"{type(e).__name__}: {e}"
                )
            else:
                if got != 3.0:  # sum over ranks of full(1024, rank+1)[0]
                    bad.append(
                        f"step {step} rank {r}: allreduce returned {got}, "
                        "want 3.0"
                    )
                else:
                    outcomes["ok"] += 1
        if fired and not (outcomes["typed_death"] or outcomes["victim_died"]):
            bad.append(
                f"step {step}: nemesis fired ({fired}) but no rank observed "
                f"a death: {outcomes}"
            )
        # Reap this step's group: surviving ranks and the rendezvous actor
        # (each step builds a fresh group, so corpses must not accumulate).
        for a in ranks:
            try:
                session.ray.kill(a)
            except Exception:
                pass
        try:
            session.ray.kill(
                session.ray.get_actor(f"__collective_{group}")
            )
        except Exception:
            pass
        return bad, fired

    interceptor = session.run_async(_install(), timeout=20)
    try:
        for step in range(scenario.steps):
            actions = plan.at_step(step)
            if scenario.workload not in ("serve", "collective"):
                for action, pick in actions:
                    async def _fire(action=action, pick=pick):
                        return await nemesis.fire(action, pick)

                    fired = session.run_async(_fire(), timeout=60)
                    if verbose and fired:
                        print(f"      nemesis: {fired}")
                    if scenario.repair and fired:
                        # Autoscaler analog: replace the killed node right
                        # away so queued infeasible work and reconstruction
                        # proceed.
                        session.repair_victim_node()
            try:
                if scenario.workload == "serve":
                    outcomes, bad, fired, err_samples = session.run_async(
                        _serve_step(step, actions), timeout=90
                    )
                    if verbose and fired:
                        for desc in fired:
                            print(f"      nemesis: {desc}")
                    violations.extend(
                        f"workload: step {step} serve: {b}" for b in bad
                    )
                    if not outcomes["ok"]:
                        violations.append(
                            f"workload: step {step} no serve request "
                            f"succeeded: {outcomes} errors={err_samples}"
                        )
                elif scenario.workload == "collective":
                    bad, fired = _collective_step(step, actions)
                    if verbose and fired:
                        for desc in fired:
                            print(f"      nemesis: {desc}")
                    violations.extend(f"workload: {b}" for b in bad)
                elif scenario.workload == "tasks":
                    refs = [
                        session.add.remote(seed * 1000 + step * 10 + i, i)
                        for i in range(4)
                    ]
                    got = session.ray.get(refs, timeout=120)
                    expect = [seed * 1000 + step * 10 + 2 * i for i in range(4)]
                    if got != expect:
                        violations.append(
                            f"workload: step {step} returned {got}, "
                            f"expected {expect}"
                        )
                elif scenario.workload == "spill":
                    # One step's slice of a working set sized 4x the arena:
                    # the puts that cannot fit force the pressure loop to
                    # spill, and refs are held for the whole seed so nothing
                    # is merely freed instead of spilled.
                    arena = scenario.object_store_memory or _SPILL_ARENA
                    per_step = max(
                        1, (4 * arena) // SPILL_BLOB_SIZE // scenario.steps
                    )
                    tags = [
                        (scenario.name, seed, step, i)
                        for i in range(per_step)
                    ]
                    refs = [session.produce_spill.remote(t) for t in tags]
                    ready, not_ready = session.ray.wait(
                        refs, num_returns=len(refs), timeout=180
                    )
                    if not_ready:
                        violations.append(
                            f"workload: step {step}: {len(not_ready)}/"
                            f"{len(refs)} produces never acknowledged"
                        )
                    acked = {r.hex() for r in ready}
                    for r, t in zip(refs, tags):
                        if r.hex() in acked:
                            data_ledger.append(
                                (r, _spill_digest(t), "task-return")
                            )
                    put_tag = ("put", scenario.name, seed, step)
                    put_ref = session.ray.put(_produce_spill_blob(put_tag))
                    data_ledger.append((put_ref, _spill_digest(put_tag), "put"))
                    # Spot-check one transfer now; the full ledger is
                    # re-resolved by check_no_data_loss after convergence.
                    data = session.ray.get(refs[0], timeout=120)
                    if hashlib.sha256(data).hexdigest() != _spill_digest(tags[0]):
                        violations.append(
                            f"workload: step {step} spilled transfer corrupt"
                        )
                    del data
                    spill_seen = max(spill_seen, sum(
                        r.spilled_bytes
                        for r in session.cluster.raylets.values()
                    ))
                else:  # transfer
                    tag = (scenario.name, seed, step)
                    ref = session.produce.remote(tag)
                    data = session.ray.get(ref, timeout=120)
                    if data != _blob(tag):
                        violations.append(
                            f"workload: step {step} transfer corrupt "
                            f"({len(data)} bytes)"
                        )
                    probe_refs.append(
                        (ref, hashlib.sha256(_blob(tag)).hexdigest())
                    )
            except Exception as e:
                violations.append(
                    f"workload: step {step} failed: {type(e).__name__}: {e}"
                )
            if scenario.router_restart:
                async def _restart_router():
                    from ray_tpu.serve import handle as handle_mod

                    handle_mod._reset_router()

                session.run_async(_restart_router(), timeout=10)
    finally:
        session.run_async(_uninstall())

    # crash_gcs durability diffs: acknowledged records missing after a
    # crash-restart are violations, not workload noise.
    violations.extend(nemesis.state_loss)

    # Belt and braces: if the in-step repair was skipped (nemesis found no
    # target), make sure the cluster shape is whole before quiescing.
    if scenario.repair:
        session.repair_victim_node()

    # Convergence: quiesce, then invariants, then functional probes.
    async def _converge():
        await invariants.quiesce(session.cluster, timeout=30.0)
        return await invariants.check(session.cluster)

    try:
        violations.extend(str(v) for v in session.run_async(_converge(), timeout=45))
    except Exception as e:
        violations.append(f"convergence: {type(e).__name__}: {e}")

    # Probe 1: previously transferred objects still resolve correctly
    # (reconstruction allowed — kill_raylet relies on it).
    for ref, digest in probe_refs:
        try:
            data = session.ray.get(ref, timeout=120)
            if hashlib.sha256(data).hexdigest() != digest:
                violations.append("probe: re-get returned corrupt bytes")
        except Exception as e:
            violations.append(
                f"probe: owned object not reconstructable: "
                f"{type(e).__name__}: {e}"
            )
    # Probe (spill): the no-data-loss invariant — every acknowledged object
    # still resolves to its exact bytes (restored from external storage or
    # re-executed from lineage), or fails with the typed reconstruction
    # error. And the pressure loop must actually have spilled along the way,
    # else the seed proved nothing about the spill path.
    if scenario.workload == "spill":
        if not spill_seen:
            violations.append(
                "workload: spill scenario never spilled (working set did "
                "not pressure the arena)"
            )
        violations.extend(
            str(v)
            for v in invariants.check_no_data_loss(
                session.ray, data_ledger, timeout_s=120.0
            )
        )
    # Probe 2: the cluster still runs fresh work.
    try:
        if session.ray.get(session.add.remote(seed, 1), timeout=60) != seed + 1:
            violations.append("probe: fresh task returned wrong value")
    except Exception as e:
        violations.append(f"probe: fresh task failed: {type(e).__name__}: {e}")
    # Probe 3 (serve): a fresh request must route and succeed — whatever the
    # faults broke (replica, router view) has been repaired by now. One retry
    # absorbs a router whose long-poll update is still in flight.
    if scenario.workload == "serve":

        async def _serve_probe():
            from ray_tpu.serve import handle as handle_mod

            router = await handle_mod._get_router()
            for attempt in (0, 1):
                try:
                    return await router.assign_request(
                        session.serve_dep,
                        {"call_method": "__call__", "request_id": "",
                         "multiplexed_model_id": ""},
                        (seed,),
                        {},
                        timeout_s=5.0,
                    )
                except Exception:
                    if attempt:
                        raise
                    await asyncio.sleep(1.0)

        try:
            if session.run_async(_serve_probe(), timeout=30) != seed:
                violations.append("probe: serve request returned wrong value")
        except Exception as e:
            violations.append(
                f"probe: serve request failed: {type(e).__name__}: {e}"
            )

    dup_avoided = sum(
        r.duplicate_lease_grants_avoided for r in session.cluster.raylets.values()
    )
    stalled = sum(
        r.pull_manager.stalled_streams for r in session.cluster.raylets.values()
    )
    rereq = sum(
        r.pull_manager.rerequested_streams
        for r in session.cluster.raylets.values()
    )
    return SeedResult(
        scenario=scenario.name,
        seed=seed,
        ok=not violations,
        schedule_digest=schedule.digest(),
        fault_log_digest=interceptor.log.digest(),
        faults_fired=interceptor.log.count(),
        violations=violations,
        duplicate_grants_avoided=dup_avoided,
        stalled_streams=stalled,
        rerequested_streams=rereq,
        deadline_shed=rpc.deadline_stats.shed,
        deadline_enforced=rpc.deadline_stats.enforced,
        spilled_bytes=spill_seen,
    )


# -- simulated-cluster scheduler seeds ---------------------------------------

# Lease cycles per step. With 120 4-CPU nodes and 2-CPU demands this holds
# the fleet at ~85% utilization, so most requests funneled through the few
# entry raylets must spill — kills then land mid-chain by construction.
_SCHED_BURST = 200


def run_sched_seed(cluster, client, scenario: Scenario, seed: int,
                   verbose: bool = False) -> SeedResult:
    """One seed of a ``sched`` scenario: saturating bursts of concurrent
    lease cycles on a SimCluster while the nemesis kills raylets mid-
    spillback-chain, then quiescence + the lease-exactly-once/ledger
    invariants on the survivors. No driver, no workers — the control plane
    under fire is the whole point."""
    from ray_tpu._private import rpc
    from ray_tpu._private import telemetry
    from ray_tpu.chaos import invariants
    from ray_tpu.chaos.nemesis import Nemesis

    schedule = FaultSchedule(seed, scenario.specs)
    plan = NemesisPlan(seed, scenario.nemesis, scenario.steps)
    nemesis = Nemesis(cluster)
    violations: List[str] = []
    fired_all: List[str] = []

    async def _reset():
        # Same per-seed hygiene as run_seed: drained cluster, fresh deadline
        # accounting and telemetry so check()/flight dumps see one seed only.
        await invariants.quiesce(cluster, timeout=15.0)
        from ray_tpu._private.gcs_store import heal_all_partitions

        heal_all_partitions()
        rpc.deadline_stats.reset()
        gcs = cluster.gcs_server
        if gcs is not None:
            gcs.worker_deadline_stats.update(met=0, shed=0, enforced=0)
            gcs.worker_deadline_stats["overruns"].clear()
            telemetry.reset_all()
            gcs.telemetry = telemetry.new_aggregate()
            gcs.flight_events.clear()
            gcs.spans.clear()
            from ray_tpu.util import tracing

            tracing.reset()

    cluster.run(_reset(), timeout=30)

    async def _sched_step(step: int, actions) -> None:
        # Funnel every request through a handful of entry raylets: they
        # saturate immediately, so the burst rides spillback chains across
        # the fleet rather than granting at the front door.
        entries = sorted(cluster.raylets)[: max(4, len(cluster.raylets) // 16)]
        addrs = [tuple(cluster.raylets[nid].addr) for nid in entries]

        async def one(i):
            await client.lease_cycle(
                {"CPU": 2.0},
                entry_addr=addrs[(seed + i) % len(addrs)],
                hold_s=0.02,
            )

        burst = asyncio.gather(
            *(one(i) for i in range(_SCHED_BURST)), return_exceptions=True
        )
        await asyncio.sleep(0.05)  # let chains get in flight before killing
        for action, pick in actions:
            desc = await nemesis.fire(action, pick)
            if desc:
                fired_all.append(desc)
                if verbose:
                    print(f"      nemesis: {desc}")
        results = await burst
        errors = [r for r in results if isinstance(r, BaseException)]
        if errors:
            sample = "; ".join(f"{type(e).__name__}: {e}" for e in errors[:3])
            violations.append(
                f"workload: step {step}: {len(errors)}/{len(results)} lease "
                f"cycles failed ({sample})"
            )

    try:
        for step in range(scenario.steps):
            cluster.run(_sched_step(step, plan.at_step(step)), timeout=180)
    finally:
        if scenario.repair:
            # Autoscaler analog: restore the fleet to its nominal size so
            # the next seed starts from the scenario's shape.
            while len(cluster.raylets) < scenario.sim_nodes:
                cluster.add_node()

    violations.extend(nemesis.state_loss)

    async def _converge():
        await invariants.quiesce(cluster, timeout=30.0)
        return await invariants.check(cluster)

    try:
        violations.extend(str(v) for v in cluster.run(_converge(), timeout=60))
    except Exception as e:
        violations.append(f"convergence: {type(e).__name__}: {e}")

    # Scheduler-specific exactly-once: every cycle released its grant (or
    # the grant died with its raylet), so no survivor may still hold one.
    for raylet in list(cluster.raylets.values()):
        if raylet.leases:
            violations.append(
                f"lease-exactly-once: node {raylet.node_id[:8]} still holds "
                f"{len(raylet.leases)} grant(s) after every cycle released"
            )

    # Probe: the surviving cluster still grants fresh leases.
    async def _probe():
        grant = await client.lease({"CPU": 1.0}, timeout=30.0)
        await client.release(grant)

    try:
        cluster.run(_probe(), timeout=45)
    except Exception as e:
        violations.append(
            f"probe: fresh lease failed: {type(e).__name__}: {e}"
        )

    dup_avoided = sum(
        r.duplicate_lease_grants_avoided for r in cluster.raylets.values()
    )
    return SeedResult(
        scenario=scenario.name,
        seed=seed,
        ok=not violations,
        schedule_digest=schedule.digest(),
        # No wire interceptor here — the fault log is the nemesis record.
        fault_log_digest=hashlib.sha256(
            "\n".join(fired_all).encode()
        ).hexdigest(),
        faults_fired=len(fired_all),
        violations=violations,
        duplicate_grants_avoided=dup_avoided,
        deadline_shed=rpc.deadline_stats.shed,
        deadline_enforced=rpc.deadline_stats.enforced,
    )


def _run_sched_scenario(scenario: Scenario, seeds: List[int],
                        corpus: Optional[str],
                        verbose: bool = False) -> List[SeedResult]:
    """Seed loop for ``sched`` scenarios: a SimCluster instead of a driver
    session, reused across seeds, rebuilt after any failing seed."""
    import shutil
    import tempfile

    from ray_tpu._private.sim_cluster import SimCluster, SimLeaseClient

    def _boot():
        persist_path = None
        if scenario.persist:
            persist_path = os.path.join(
                tempfile.mkdtemp(prefix="chaos_gcs_"), "gcs.wal"
            )
        cluster = SimCluster(
            scenario.sim_nodes, env=dict(scenario.env),
            persist_path=persist_path, ha=scenario.ha,
        ).start()
        return cluster, SimLeaseClient(cluster)

    def _teardown(cluster, client):
        try:
            cluster.run(client.close(), timeout=30)
        except Exception:
            pass
        cluster.shutdown()
        if cluster.persist_path:
            shutil.rmtree(os.path.dirname(cluster.persist_path),
                          ignore_errors=True)

    results: List[SeedResult] = []
    cluster, client = _boot()
    try:
        for seed in seeds:
            result = run_sched_seed(cluster, client, scenario, seed,
                                    verbose=verbose)
            results.append(result)
            status = "ok" if result.ok else "FAIL"
            print(
                f"    seed {seed:>4} {status}  faults={result.faults_fired}"
                f"  schedule={result.schedule_digest[:12]}"
            )
            if not result.ok:
                for v in result.violations:
                    print(f"      {v}")
                if corpus:
                    _append_corpus(corpus, result)
                # One bad seed must not poison the next: fresh sim cluster.
                _teardown(cluster, client)
                cluster, client = _boot()
    finally:
        _teardown(cluster, client)
    return results


def run_scenario(scenario: Scenario, seeds: List[int], corpus: Optional[str],
                 verbose: bool = False) -> List[SeedResult]:
    if scenario.workload == "sched":
        return _run_sched_scenario(scenario, seeds, corpus, verbose=verbose)
    results: List[SeedResult] = []
    session = _Session(scenario)
    try:
        for seed in seeds:
            result = run_seed(session, scenario, seed, verbose=verbose)
            results.append(result)
            status = "ok" if result.ok else "FAIL"
            print(
                f"    seed {seed:>4} {status}  faults={result.faults_fired}"
                f"  schedule={result.schedule_digest[:12]}"
            )
            if not result.ok:
                for v in result.violations:
                    print(f"      {v}")
                if corpus:
                    _append_corpus(corpus, result)
                    _dump_flight(corpus, session, result)
                    _dump_spans(corpus, session, result)
                # One bad seed must not poison the next: fresh cluster.
                session.close()
                session = _Session(scenario)
    finally:
        session.close()
    return results


def _append_corpus(path: str, result: SeedResult) -> None:
    with open(path, "a") as f:
        f.write(json.dumps(result.to_wire(), sort_keys=True) + "\n")


def _dump_flight(corpus: str, session: _Session, result: SeedResult) -> Optional[str]:
    """Write the merged flight-recorder timeline for a failing seed next to
    the replay corpus: the GCS's ingested ring (events drained from worker
    and driver flushes) merged with this process's undrained local ring,
    sorted by wall-clock timestamp."""
    from ray_tpu._private import telemetry

    gcs = session.cluster.gcs_server
    ingested = list(gcs.flight_events) if gcs is not None else []
    path = os.path.join(
        os.path.dirname(os.path.abspath(corpus)),
        f"flight_{result.scenario}_{result.seed}.jsonl",
    )
    try:
        n = telemetry.dump_timeline(
            path, ingested, telemetry.flight().snapshot()
        )
    except Exception as e:  # triage artifact must never mask the violation
        print(f"      flight dump failed: {type(e).__name__}: {e}")
        return None
    print(f"      flight recorder: {n} events -> {path}")
    return path


def _dump_spans(corpus: str, session: _Session, result: SeedResult) -> Optional[str]:
    """Write the merged span timeline for a failing seed as chrome://tracing
    JSON next to the flight-recorder dump: the GCS's span ring (flushed from
    workers and diverted from task events) merged with this process's
    unflushed local buffer. Loads directly into Perfetto for causal triage."""
    from ray_tpu.util import tracing
    from ray_tpu.util.state.api import _span_timeline_events

    gcs = session.cluster.gcs_server
    spans = list(gcs.spans) if gcs is not None else []
    spans.extend(tracing.snapshot())
    if not spans:
        return None
    path = os.path.join(
        os.path.dirname(os.path.abspath(corpus)),
        f"spans_{result.scenario}_{result.seed}.json",
    )
    try:
        events = _span_timeline_events(spans)
        with open(path, "w") as f:
            json.dump(events, f)
    except Exception as e:  # triage artifact must never mask the violation
        print(f"      span dump failed: {type(e).__name__}: {e}")
        return None
    print(f"      span timeline: {len(events)} spans -> {path}")
    return path


def _load_corpus(path: str) -> List[dict]:
    entries = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                entries.append(json.loads(line))
    return entries


# -- determinism gate --------------------------------------------------------


def check_determinism(names: List[str], seeds: List[int]) -> int:
    """Rebuild every (scenario, seed) schedule twice and compare bytes; the
    CI proof that replaying a seed reproduces the identical fault plan."""
    failures = 0
    for name in names:
        scenario = SCENARIOS[name]
        for seed in seeds:
            a = FaultSchedule(seed, scenario.specs)
            b = FaultSchedule(seed, scenario.specs)
            pa = NemesisPlan(seed, scenario.nemesis, scenario.steps)
            pb = NemesisPlan(seed, scenario.nemesis, scenario.steps)
            same = a.to_bytes() == b.to_bytes() and pa.to_wire() == pb.to_wire()
            if not same:
                failures += 1
                print(f"  {name} seed {seed}: NON-DETERMINISTIC SCHEDULE")
            else:
                print(f"  {name} seed {seed}: {a.digest()[:16]} deterministic")
    return failures


# -- CLI ---------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.chaos",
        description="deterministic seeded fault injection with convergence "
        "invariants",
    )
    parser.add_argument("--suite", choices=sorted(SUITES), default=None,
                        help="named scenario suite")
    parser.add_argument("--scenario", action="append", default=None,
                        help="individual scenario (repeatable)")
    parser.add_argument("--seeds", type=int, default=5,
                        help="number of consecutive seeds (default 5)")
    parser.add_argument("--seed-base", type=int, default=0,
                        help="first seed (default 0)")
    parser.add_argument("--seed", action="append", type=int, default=None,
                        help="explicit seed (repeatable; overrides --seeds)")
    parser.add_argument("--corpus", default="chaos_corpus.jsonl",
                        help="JSONL replay corpus for failing seeds")
    parser.add_argument("--no-corpus", action="store_true",
                        help="do not record failing seeds")
    parser.add_argument("--replay", metavar="PATH",
                        help="re-run the (scenario, seed) entries of a corpus")
    parser.add_argument("--check-determinism", action="store_true",
                        help="only verify seed -> schedule determinism "
                        "(no cluster)")
    parser.add_argument("--list", action="store_true",
                        help="list scenarios and suites")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)

    if args.list:
        print("scenarios:")
        for name in sorted(SCENARIOS):
            s = SCENARIOS[name]
            nem = f" nemesis={','.join(s.nemesis)}" if s.nemesis else ""
            print(f"  {name:<14} {s.description}{nem}")
        print("suites:")
        for name in sorted(SUITES):
            print(f"  {name:<14} {' '.join(SUITES[name])}")
        return 0

    if args.replay:
        entries = _load_corpus(args.replay)
        if not entries:
            print(f"replay corpus {args.replay} is empty")
            return 0
        pairs = [(e["scenario"], e["seed"]) for e in entries]
        names = sorted({s for s, _ in pairs})
        rc = 0
        for name in names:
            scenario = SCENARIOS[name]
            seeds = sorted({seed for s, seed in pairs if s == name})
            print(f"  replay {name} seeds {seeds}")
            results = run_scenario(scenario, seeds, corpus=None,
                                   verbose=args.verbose)
            rc |= int(any(not r.ok for r in results))
        return rc

    names = list(args.scenario or [])
    if args.suite:
        names.extend(SUITES[args.suite])
    if not names:
        names = SUITES["smoke"]
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        print(f"unknown scenario(s): {unknown}; see --list")
        return 2
    seeds = args.seed if args.seed else list(
        range(args.seed_base, args.seed_base + args.seeds)
    )

    if args.check_determinism:
        failures = check_determinism(names, seeds)
        print(
            "determinism: "
            + ("FAILED" if failures else f"ok ({len(names) * len(seeds)} schedules)")
        )
        return 1 if failures else 0

    corpus = None if args.no_corpus else args.corpus
    total_fail = 0
    for name in names:
        scenario = SCENARIOS[name]
        print(f"chaos scenario {name}: {scenario.description}")
        results = run_scenario(scenario, seeds, corpus, verbose=args.verbose)
        failed = [r for r in results if not r.ok]
        total_fail += len(failed)
        print(
            f"  {name}: {len(results) - len(failed)}/{len(results)} seeds "
            "converged"
        )
    if total_fail:
        print(f"chaos: {total_fail} failing seed(s)"
              + (f" recorded to {corpus}" if corpus else ""))
        return 1
    print("chaos: all seeds converged; every invariant held")
    return 0


if __name__ == "__main__":
    sys.exit(main())
