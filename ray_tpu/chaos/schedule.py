"""Deterministic seeded fault schedules.

A schedule is derived entirely from ``(seed, specs)`` at construction time:
for every :class:`FaultSpec` a dedicated ``random.Random`` (seeded from a
stable SHA-256 derivation — never the salted builtin ``hash``) pre-computes a
finite decision stream indexed by *match number*. The runtime interceptor
only ever consumes decisions by match index, so the planned fault sequence is
a pure function of the seed: replaying a seed replays byte-identical faults
against the same traffic, and ``to_bytes()`` of two schedules built from the
same seed compare equal (the CI determinism gate).

Jepsen's nemesis schedules inspired the shape; the determinism requirement
(seed -> identical fault sequence -> replayable failure) comes from this
repo's convergence story: a failing seed lands in the replay corpus and any
future PR can re-run exactly that fault sequence against the runtime.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

FAULT_KINDS = ("drop", "delay", "dup", "reorder")
FRAME_CLASSES = ("request", "reply", "push", "any")

# How many matches per spec get a pre-computed decision. Matches past the
# horizon flow through un-faulted — a bounded plan keeps serialization small
# and makes "the schedule" a finite, comparable artifact.
DEFAULT_HORIZON = 2048


def stable_u64(text: str) -> int:
    """Deterministic 64-bit digest of a string (process- and run-stable,
    unlike builtin ``hash`` which is salted per interpreter)."""
    return int.from_bytes(hashlib.sha256(text.encode()).digest()[:8], "big")


@dataclass(frozen=True)
class FaultSpec:
    """One fault rule: which frames it matches and what it may do to them.

    ``method`` is an ``fnmatch`` pattern over RPC method names ("PushChunk",
    "Request*"). ``frame`` narrows by frame class: request / reply (normal +
    error replies) / push (one-way) / any. ``p`` is the per-match fire
    probability; ``start_after`` exempts the first N matches so bring-up
    traffic is never faulted; ``max_fires`` caps total fires (< 0: unbounded).
    """

    name: str
    kind: str  # drop | delay | dup | reorder
    method: str
    frame: str = "any"
    p: float = 1.0
    delay_s: Tuple[float, float] = (0.01, 0.05)
    start_after: int = 0
    max_fires: int = -1

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.frame not in FRAME_CLASSES:
            raise ValueError(f"unknown frame class {self.frame!r}")

    def to_wire(self) -> dict:
        return {
            "name": self.name,
            "kind": self.kind,
            "method": self.method,
            "frame": self.frame,
            "p": self.p,
            "delay_s": list(self.delay_s),
            "start_after": self.start_after,
            "max_fires": self.max_fires,
        }


# A decision is None (let the frame through) or a tuple ("drop",) /
# ("delay", seconds) / ("dup",) / ("reorder",).
Decision = Optional[Tuple]


class FaultSchedule:
    """Seed-deterministic plan: spec name -> decision per match index."""

    def __init__(
        self,
        seed: int,
        specs: Sequence[FaultSpec],
        horizon: int = DEFAULT_HORIZON,
    ):
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate spec names in {names}")
        self.seed = int(seed)
        self.specs: List[FaultSpec] = list(specs)
        self.horizon = int(horizon)
        self.decisions: Dict[str, List[Decision]] = {
            spec.name: self._plan_spec(spec) for spec in self.specs
        }

    def _plan_spec(self, spec: FaultSpec) -> List[Decision]:
        import random

        rng = random.Random(stable_u64(f"{self.seed}:{spec.name}"))
        plan: List[Decision] = []
        fires = 0
        for i in range(self.horizon):
            if i < spec.start_after:
                plan.append(None)
                continue
            roll = rng.random()
            capped = 0 <= spec.max_fires <= fires
            if capped or roll >= spec.p:
                plan.append(None)
                continue
            fires += 1
            if spec.kind == "delay":
                lo, hi = spec.delay_s
                # Round so the serialized schedule is float-stable.
                plan.append(("delay", round(rng.uniform(lo, hi), 6)))
            else:
                plan.append((spec.kind,))
        return plan

    def decision(self, spec_name: str, match_index: int) -> Decision:
        plan = self.decisions[spec_name]
        if match_index >= len(plan):
            return None
        return plan[match_index]

    def to_wire(self) -> dict:
        return {
            "seed": self.seed,
            "horizon": self.horizon,
            "specs": [s.to_wire() for s in self.specs],
            "decisions": {
                name: [list(d) if d is not None else None for d in plan]
                for name, plan in self.decisions.items()
            },
        }

    def to_bytes(self) -> bytes:
        """Canonical serialization; byte-identical for identical seeds."""
        return json.dumps(
            self.to_wire(), sort_keys=True, separators=(",", ":")
        ).encode()

    def digest(self) -> str:
        return hashlib.sha256(self.to_bytes()).hexdigest()


@dataclass
class FaultEvent:
    """One fault that actually fired at runtime."""

    spec: str
    match_index: int
    action: Tuple
    method: str
    kind: int  # wire frame kind (0 req / 1 rep / 2 err / 3 push)

    def to_wire(self) -> dict:
        return {
            "spec": self.spec,
            "match_index": self.match_index,
            "action": list(self.action),
            "method": self.method,
            "kind": self.kind,
        }


@dataclass
class FaultLog:
    """Append-only record of fired faults; the runtime half of the replay
    story (the schedule is the planned half)."""

    events: List[FaultEvent] = field(default_factory=list)

    def record(self, event: FaultEvent) -> None:
        self.events.append(event)

    def count(self, spec: Optional[str] = None) -> int:
        if spec is None:
            return len(self.events)
        return sum(1 for e in self.events if e.spec == spec)

    def to_wire(self) -> list:
        return [e.to_wire() for e in self.events]

    def to_bytes(self) -> bytes:
        return json.dumps(self.to_wire(), separators=(",", ":")).encode()

    def digest(self) -> str:
        return hashlib.sha256(self.to_bytes()).hexdigest()


class NemesisPlan:
    """Seed-deterministic plan for process-level fault actions.

    For a workload of ``steps`` checkpoints, decides at which step indices
    each nemesis action fires and pre-draws the integer used for victim
    selection (index modulo candidate count at fire time, over a sorted
    candidate list — the pick is deterministic whenever cluster membership
    at the fire point is, which a deterministic schedule arranges).
    """

    def __init__(self, seed: int, actions: Sequence[str], steps: int):
        import random

        self.seed = int(seed)
        self.actions = list(actions)
        self.steps = int(steps)
        self.points: List[Tuple[int, str, int]] = []  # (step, action, pick)
        for action in self.actions:
            rng = random.Random(stable_u64(f"{seed}:nemesis:{action}"))
            # One fire per action per run, never at step 0 (let the workload
            # establish state worth destroying first).
            step = rng.randrange(1, max(2, self.steps))
            self.points.append((step, action, rng.randrange(1 << 30)))
        self.points.sort(key=lambda t: (t[0], t[1]))

    def at_step(self, step: int) -> List[Tuple[str, int]]:
        return [(a, pick) for (s, a, pick) in self.points if s == step]

    def to_wire(self) -> dict:
        return {
            "seed": self.seed,
            "steps": self.steps,
            "points": [list(p) for p in self.points],
        }
