import sys

from ray_tpu.chaos.runner import main

sys.exit(main())
