"""Process-level nemesis actions (Jepsen's nemesis, scoped to the
in-process cluster harness): kill a worker process, kill a raylet, restart
the GCS. Victim selection is by plan-provided pick index over a *sorted*
candidate list so a replayed seed attacks the same victim whenever cluster
membership at the fire point matches.

Every action is something the runtime promises to survive: killed workers
are re-leased and their tasks retried, killed raylets trigger lineage
reconstruction on surviving nodes, a restarted GCS resumes from its
persisted tables.
"""

from __future__ import annotations

import logging
from typing import List, Optional

logger = logging.getLogger(__name__)

ACTIONS = (
    "kill_worker", "kill_replica", "kill_raylet", "restart_gcs", "crash_gcs",
    "kill_collective_rank", "kill_gcs_host", "partition_follower",
    "heal_partition", "partition_majority",
)

# Actor-name prefix of Serve replica workers (ReplicaID.to_actor_name).
SERVE_REPLICA_PREFIX = "SERVE_REPLICA::"

# Actor-name prefix of collective-group rank actors (the chaos collective
# workload names its ranks this way). The group's rendezvous actor
# (`__collective_*`) is deliberately NOT a target: killing it exercises the
# same store-death path, but the invariant under test is peer death
# detection mid-op (docs/collectives.md "Failure semantics").
COLLECTIVE_RANK_PREFIX = "COLLECTIVE_RANK::"


class Nemesis:
    """Fires plan points against a live :class:`~ray_tpu.cluster_utils.Cluster`.

    ``protect_head``: the head raylet hosts the driver's object store in the
    smoke scenarios, so kill_raylet targets non-head nodes when any exist.
    """

    def __init__(self, cluster, protect_head: bool = True):
        self.cluster = cluster
        self.protect_head = protect_head
        self.actions_fired: List[str] = []
        # crash_gcs durability violations (acknowledged control-plane state
        # missing after the crash-restart); the runner folds these into the
        # seed's violation list.
        self.state_loss: List[str] = []

    async def fire(self, action: str, pick: int) -> Optional[str]:
        """Run one action; returns a human-readable description (or None if
        no eligible target existed — e.g. no spawned workers yet)."""
        if action == "kill_worker":
            return self._kill_worker(pick)
        if action == "kill_replica":
            return self._kill_replica(pick)
        if action == "kill_collective_rank":
            return self._kill_collective_rank(pick)
        if action == "kill_raylet":
            return await self._kill_raylet(pick)
        if action == "restart_gcs":
            return await self._restart_gcs()
        if action == "crash_gcs":
            return await self._crash_gcs()
        if action == "kill_gcs_host":
            return await self._kill_gcs_host()
        if action == "partition_follower":
            return self._partition_follower(pick)
        if action == "heal_partition":
            return self._heal_partition()
        if action == "partition_majority":
            return await self._partition_majority()
        raise ValueError(f"unknown nemesis action {action!r}")

    def _kill_worker(self, pick: int) -> Optional[str]:
        candidates = []
        for node_id in sorted(self.cluster.raylets):
            raylet = self.cluster.raylets[node_id]
            for worker_id in sorted(raylet.workers):
                handle = raylet.workers[worker_id]
                if handle.proc is not None and handle.proc.returncode is None:
                    candidates.append((node_id, worker_id, handle))
        if not candidates:
            return None
        node_id, worker_id, handle = candidates[pick % len(candidates)]
        try:
            handle.proc.kill()  # SIGKILL: no atexit, no farewell RPC
        except ProcessLookupError:
            return None
        self.actions_fired.append("kill_worker")
        logger.info("nemesis: killed worker %s on %s", worker_id[:8], node_id[:8])
        return f"kill_worker {worker_id[:8]}@{node_id[:8]}"

    def _kill_replica(self, pick: int) -> Optional[str]:
        """SIGKILL a worker hosting a Serve *replica* actor — never the
        controller or proxy, whose loss is a control-plane outage rather than
        the data-plane fault the serve scenarios exercise. The controller's
        health loop must replace the replica and routers must route around
        the corpse."""
        gcs = self.cluster.gcs_server
        if gcs is None:
            return None
        replica_workers = {
            a.worker_id
            for a in gcs.actors.values()
            if a.state == "ALIVE"
            and (a.name or "").startswith(SERVE_REPLICA_PREFIX)
            and a.worker_id
        }
        candidates = []
        for node_id in sorted(self.cluster.raylets):
            raylet = self.cluster.raylets[node_id]
            for worker_id in sorted(raylet.workers):
                if worker_id not in replica_workers:
                    continue
                handle = raylet.workers[worker_id]
                if handle.proc is not None and handle.proc.returncode is None:
                    candidates.append((node_id, worker_id, handle))
        if not candidates:
            return None
        node_id, worker_id, handle = candidates[pick % len(candidates)]
        try:
            handle.proc.kill()
        except ProcessLookupError:
            return None
        self.actions_fired.append("kill_replica")
        logger.info(
            "nemesis: killed serve replica worker %s on %s",
            worker_id[:8],
            node_id[:8],
        )
        return f"kill_replica {worker_id[:8]}@{node_id[:8]}"

    def _kill_collective_rank(self, pick: int) -> Optional[str]:
        """SIGKILL a worker hosting a collective-group rank actor while its
        group op is in flight. The surviving ranks' blocked ops must fail
        with a typed CollectiveGroupDiedError within the health deadline —
        never hang (docs/collectives.md)."""
        gcs = self.cluster.gcs_server
        if gcs is None:
            return None
        rank_workers = {
            a.worker_id
            for a in gcs.actors.values()
            if a.state == "ALIVE"
            and (a.name or "").startswith(COLLECTIVE_RANK_PREFIX)
            and a.worker_id
        }
        candidates = []
        for node_id in sorted(self.cluster.raylets):
            raylet = self.cluster.raylets[node_id]
            for worker_id in sorted(raylet.workers):
                if worker_id not in rank_workers:
                    continue
                handle = raylet.workers[worker_id]
                if handle.proc is not None and handle.proc.returncode is None:
                    candidates.append((node_id, worker_id, handle))
        if not candidates:
            return None
        node_id, worker_id, handle = candidates[pick % len(candidates)]
        try:
            handle.proc.kill()
        except ProcessLookupError:
            return None
        self.actions_fired.append("kill_collective_rank")
        logger.info(
            "nemesis: killed collective rank worker %s on %s",
            worker_id[:8],
            node_id[:8],
        )
        return f"kill_collective_rank {worker_id[:8]}@{node_id[:8]}"

    async def _kill_raylet(self, pick: int) -> Optional[str]:
        head_id = (
            self.cluster.head_node.raylet.node_id
            if self.cluster.head_node is not None
            else None
        )
        candidates = [
            nid
            for nid in sorted(self.cluster.raylets)
            if not (self.protect_head and nid == head_id)
        ]
        if not candidates:
            return None
        node_id = candidates[pick % len(candidates)]
        raylet = self.cluster.raylets.pop(node_id)
        await raylet.stop()
        self.actions_fired.append("kill_raylet")
        logger.info("nemesis: killed raylet %s", node_id[:8])
        return f"kill_raylet {node_id[:8]}"

    async def _restart_gcs(self) -> Optional[str]:
        node = self.cluster.head_node
        if node is None or node.gcs_server is None:
            return None
        await node.kill_gcs()
        await node.restart_gcs()
        # cluster_utils keeps its own reference for shutdown(); refresh it.
        self.cluster.gcs_server = node.gcs_server
        self.actions_fired.append("restart_gcs")
        logger.info("nemesis: restarted GCS")
        return "restart_gcs"

    async def _crash_gcs(self) -> Optional[str]:
        """Hard-crash the GCS — no store checkpoint, no final fsync, a torn
        half-record on the WAL tail — then restart it and diff the restored
        control-plane tables against the pre-crash picture. Every record
        acknowledged before the crash must survive (group commit flushes to
        the OS on crash; only an OS-level crash may lose the last tick)."""
        gcs = self.cluster.gcs_server
        if gcs is None:
            return None
        from ray_tpu._private.gcs_store import InMemoryStoreClient

        durable = not isinstance(gcs.store, InMemoryStoreClient)
        pre = {
            "actors": set(gcs.actors),
            "pgs": set(gcs.placement_groups),
            "jobs": set(gcs.jobs),
            "named": dict(gcs.named_actors),
            "kv": dict(gcs.kv),
        }
        node = self.cluster.head_node
        if node is not None and node.gcs_server is not None:
            await node.crash_gcs(torn_tail=True)
            await node.restart_gcs()
            self.cluster.gcs_server = node.gcs_server
        elif hasattr(self.cluster, "crash_gcs_async"):
            # SimCluster shape: no Node wrapper, the sim owns its GCS.
            if not await self.cluster.crash_gcs_async(torn_tail=True):
                return None
        else:
            return None
        if durable:
            new = self.cluster.gcs_server
            post = {
                "actors": set(new.actors),
                "pgs": set(new.placement_groups),
                "jobs": set(new.jobs),
            }
            for table in ("actors", "pgs", "jobs"):
                lost = pre[table] - post[table]
                if lost:
                    self.state_loss.append(
                        f"state-loss: {len(lost)} {table} record(s) gone "
                        f"after crash-restart (e.g. {sorted(lost)[:3]})"
                    )
            for (ns, name), aid in pre["named"].items():
                if new.named_actors.get((ns, name)) != aid:
                    self.state_loss.append(
                        f"state-loss: named actor {ns}/{name} -> {aid[:8]} "
                        "gone after crash-restart"
                    )
            for key, value in pre["kv"].items():
                if new.kv.get(key) != value:
                    self.state_loss.append(
                        f"state-loss: kv {key} changed/gone after "
                        "crash-restart"
                    )
        self.actions_fired.append("crash_gcs")
        logger.info("nemesis: crashed GCS (torn WAL tail) and restarted")
        return "crash_gcs"

    async def _kill_gcs_host(self) -> Optional[str]:
        """Lose the whole GCS *machine* (process killed hard AND its local
        replicated-log member dropped), then wait for the warm standby to
        promote over the surviving follower log. Every record acknowledged
        before the kill must be present in the new leader's tables — the
        zero-acknowledged-state-loss invariant for HA failover
        (docs/fault_tolerance.md "HA deployment")."""
        gcs = self.cluster.gcs_server
        if gcs is None:
            return None
        pre = {
            "actors": set(gcs.actors),
            "pgs": set(gcs.placement_groups),
            "jobs": set(gcs.jobs),
            "named": dict(gcs.named_actors),
            "kv": dict(gcs.kv),
        }
        pre_term = gcs.leader_term
        node = self.cluster.head_node
        if node is not None and getattr(node, "gcs_standby", None) is not None:
            await node.kill_gcs_host()
            self.cluster.gcs_server = node.gcs_server
        elif hasattr(self.cluster, "kill_gcs_host_async"):
            # SimCluster shape: no Node wrapper, the sim owns its GCS.
            if not await self.cluster.kill_gcs_host_async():
                return None
        else:
            return None
        new = self.cluster.gcs_server
        if new.leader_term <= pre_term:
            self.state_loss.append(
                f"split-brain: promoted leader term {new.leader_term} did "
                f"not advance past {pre_term}"
            )
        post = {
            "actors": set(new.actors),
            "pgs": set(new.placement_groups),
            "jobs": set(new.jobs),
        }
        for table in ("actors", "pgs", "jobs"):
            lost = pre[table] - post[table]
            if lost:
                self.state_loss.append(
                    f"state-loss: {len(lost)} {table} record(s) gone "
                    f"after failover (e.g. {sorted(lost)[:3]})"
                )
        for (ns, name), aid in pre["named"].items():
            if new.named_actors.get((ns, name)) != aid:
                self.state_loss.append(
                    f"state-loss: named actor {ns}/{name} -> {aid[:8]} "
                    "gone after failover"
                )
        for key, value in pre["kv"].items():
            if new.kv.get(key) != value:
                self.state_loss.append(
                    f"state-loss: kv {key} changed/gone after failover"
                )
        self.actions_fired.append("kill_gcs_host")
        logger.info(
            "nemesis: killed GCS host; standby promoted at term %d",
            new.leader_term,
        )
        return f"kill_gcs_host term={new.leader_term}"

    # -- replication-group partitions (docs/fault_tolerance.md §HA) ----------

    def _gcs_persist_path(self) -> Optional[str]:
        node = getattr(self.cluster, "head_node", None)
        if node is not None and hasattr(node, "gcs_persist_path"):
            return node.gcs_persist_path()
        return getattr(self.cluster, "persist_path", None)

    def _partition_follower(self, pick: int) -> Optional[str]:
        """Partition one follower member away from the leader — a strict
        minority of a ≥3-member group. The quorum-ack contract says this
        must NOT stall or demote the leader: commits keep acking on the
        remaining majority while the partitioned member's lag grows."""
        import os

        from ray_tpu._private.gcs_store import (
            follower_paths, partition_host, partitioned_hosts,
        )

        gcs = self.cluster.gcs_server
        path = self._gcs_persist_path()
        if gcs is None or not path:
            return None
        followers = follower_paths(path)
        # One partition at a time: this action models a minority fault, and
        # stacking it must not silently become a majority partition.
        if partitioned_hosts() or len(followers) < 2:
            return None
        target = followers[pick % len(followers)]
        partition_host(target)
        self.actions_fired.append("partition_follower")
        logger.info("nemesis: partitioned follower %s", os.path.basename(target))
        return f"partition_follower {os.path.basename(target)}"

    def _heal_partition(self) -> Optional[str]:
        """Heal every injected partition. Before healing, verify the
        minority partition did not demote the leader — commits must have
        kept flowing on the majority the whole time."""
        from ray_tpu._private.gcs_store import heal_all_partitions, partitioned_hosts

        if not partitioned_hosts():
            return None
        gcs = self.cluster.gcs_server
        if gcs is not None and gcs.fenced:
            self.state_loss.append(
                "quorum: leader demoted under a minority partition "
                "(commits must keep acking on the majority)"
            )
        heal_all_partitions()
        self.actions_fired.append("heal_partition")
        logger.info("nemesis: healed all partitions")
        return "heal_partition"

    async def _partition_majority(self) -> Optional[str]:
        """Partition EVERY follower away from the leader: no write can
        reach a majority, so the leader must demote itself (fence, typed
        StaleLeaderError to clients) rather than ack unreplicated writes.
        After healing, the standby promotes at a higher term and every
        record acknowledged before the partition must survive."""
        import asyncio

        from ray_tpu._private.common import config
        from ray_tpu._private.gcs_store import (
            follower_paths, heal_all_partitions, partition_host,
        )

        gcs = self.cluster.gcs_server
        path = self._gcs_persist_path()
        if gcs is None or not path:
            return None
        node = getattr(self.cluster, "head_node", None)
        has_standby = (
            node is not None and getattr(node, "gcs_standby", None) is not None
        ) or hasattr(self.cluster, "adopt_promoted_gcs_async")
        if not has_standby:
            return None
        pre = {
            "actors": set(gcs.actors),
            "pgs": set(gcs.placement_groups),
            "jobs": set(gcs.jobs),
            "named": dict(gcs.named_actors),
            "kv": dict(gcs.kv),
        }
        pre_term = gcs.leader_term
        for f in follower_paths(path):
            partition_host(f)
        # The leader discovers the loss on its next group commit — at the
        # latest the lease renewal, every lease/3. Wait for the demotion.
        deadline = config.gcs_leader_lease_s * 4.0 + 5.0
        waited = 0.0
        while not gcs.fenced and waited < deadline:
            await asyncio.sleep(0.05)
            waited += 0.05
        if not gcs.fenced:
            heal_all_partitions()
            self.state_loss.append(
                "quorum: leader kept serving with every follower partitioned "
                "(must demote rather than ack unreplicated writes)"
            )
            return None
        heal_all_partitions()
        # With the partition healed the standby promotes past the demoted
        # leader; adopt the new server like kill_gcs_host does.
        if node is not None and getattr(node, "gcs_standby", None) is not None:
            await node.adopt_promoted_gcs()
            self.cluster.gcs_server = node.gcs_server
        else:
            if not await self.cluster.adopt_promoted_gcs_async():
                return None
        new = self.cluster.gcs_server
        if new.leader_term <= pre_term:
            self.state_loss.append(
                f"split-brain: promoted leader term {new.leader_term} did "
                f"not advance past {pre_term} after majority partition"
            )
        post = {
            "actors": set(new.actors),
            "pgs": set(new.placement_groups),
            "jobs": set(new.jobs),
        }
        for table in ("actors", "pgs", "jobs"):
            lost = pre[table] - post[table]
            if lost:
                self.state_loss.append(
                    f"state-loss: {len(lost)} {table} record(s) gone "
                    f"after majority-partition failover (e.g. {sorted(lost)[:3]})"
                )
        for (ns, name), aid in pre["named"].items():
            if new.named_actors.get((ns, name)) != aid:
                self.state_loss.append(
                    f"state-loss: named actor {ns}/{name} -> {aid[:8]} "
                    "gone after majority-partition failover"
                )
        for key, value in pre["kv"].items():
            if new.kv.get(key) != value:
                self.state_loss.append(
                    f"state-loss: kv {key} changed/gone after "
                    "majority-partition failover"
                )
        self.actions_fired.append("partition_majority")
        logger.info(
            "nemesis: majority partition -> leader demoted, standby "
            "promoted at term %d",
            new.leader_term,
        )
        return f"partition_majority term={new.leader_term}"
