"""Convergence invariants: what must be true once the dust settles.

After a fault sequence, :func:`quiesce` drives the in-process cluster to a
stable point (no queued leases, no half-assembled pushes, no transitional
actor states), then :func:`check` asserts the invariants the recovery
machinery promises:

- **lease-exactly-once** — every lease id maps to exactly one live worker,
  no worker is under two lease ids or simultaneously leased and idle, and
  the raylet's resource ledger balances (available + leased demands == total
  when no placement groups mutate totals).
- **actors-terminal** — every GCS actor FSM is in a terminal-or-stable state
  (ALIVE / DEAD), never parked in PENDING_CREATION / RESTARTING /
  DEPENDENCIES_UNREADY after quiescence.
- **no-orphaned-tasks** — no transient coroutine (grant, RPC dispatch,
  object push) is still pending across two spaced snapshots; daemon loops
  are exempt.
- **store-settled** — no unsealed push assemblies or in-flight restores
  survive quiescence.
- **objects-reconstructable** — checked by the runner functionally: refs
  created before the faults must still ``get`` correctly (recovery may
  re-execute lineage), and a fresh probe task must run. Both are workload
  probes rather than state inspections, so they live in the runner.
- **no-data-loss** — for spill scenarios: every acknowledged put or task
  return with a live ref still resolves to its exact bytes post-quiesce
  (restored from external storage or re-executed from lineage), or fails
  with the typed :class:`ObjectReconstructionFailedError` — never wrong
  bytes, never a hang, never an untyped error
  (:func:`check_no_data_loss`).

All coroutines here run on the cluster's event loop.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import List

from ray_tpu._private import rpc

# Coroutine qualnames that MUST complete by quiescence; anything else
# pending in the background-task set is assumed to be a daemon loop.
TRANSIENT_QUALNAMES = {
    "Raylet._grant",
    "Raylet._resolve_duplicate_lease_async",
    "PushManager.push",
    "PushManager._do_push",
}

# GCS actor states that may legitimately persist after quiescence. This set
# must equal the actor machine's quiescent states declared in
# ray_tpu/devtools/protocols.py — the protocol checker (part of `make lint`)
# fails with protocol-invariant-drift if the two ever diverge, so a spec
# change here forces the matching FSM spec/doc update and vice versa.
TERMINAL_ACTOR_STATES = {"ALIVE", "DEAD"}


@dataclass
class Violation:
    invariant: str
    node_id: str
    detail: str

    def __str__(self) -> str:
        return f"[{self.invariant}] node={self.node_id[:8]}: {self.detail}"


class ConvergenceTimeout(AssertionError):
    """The cluster failed to reach quiescence inside the deadline."""


def _raylet_busy(raylet) -> List[str]:
    """What still churns on one raylet (empty == quiescent)."""
    busy = []
    if any(not req.fut.done() for req in raylet.pending_leases):
        busy.append(f"pending_leases={len(raylet.pending_leases)}")
    if raylet.grants_in_flight:
        busy.append(f"grants_in_flight={raylet.grants_in_flight}")
    if raylet.push_assembly:
        busy.append(f"push_assembly={sorted(raylet.push_assembly)}")
    if raylet.restoring:
        busy.append(f"restoring={sorted(raylet.restoring)}")
    if raylet.spilling:
        busy.append(f"spilling={sorted(raylet.spilling)}")
    # Non-actor leases drain once the driver's lease pool returns idle
    # workers (worker_lease_idle_keep_s); actor leases persist by design.
    task_leases = [
        lid for lid, h in raylet.leases.items() if h.actor_id is None
    ]
    if task_leases:
        busy.append(f"task_leases={task_leases}")
    return busy


def _gcs_busy(gcs_server) -> List[str]:
    busy = []
    transitional = {
        aid: a.state
        for aid, a in gcs_server.actors.items()
        if a.state not in TERMINAL_ACTOR_STATES
    }
    if transitional:
        busy.append(f"transitional_actors={transitional}")
    return busy


async def quiesce(cluster, timeout: float = 30.0) -> None:
    """Poll until every raylet and the GCS stop churning; raise
    :class:`ConvergenceTimeout` (with the stuck state named) otherwise."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    last: List[str] = ["never-sampled"]
    while loop.time() < deadline:
        last = []
        for raylet in list(cluster.raylets.values()):
            for item in _raylet_busy(raylet):
                last.append(f"{raylet.node_id[:8]}:{item}")
        if cluster.gcs_server is not None:
            for item in _gcs_busy(cluster.gcs_server):
                last.append(f"gcs:{item}")
        if not last:
            return
        await asyncio.sleep(0.05)
    raise ConvergenceTimeout(f"cluster did not quiesce in {timeout}s: {last}")


def check_leases(raylet) -> List[Violation]:
    """Lease table / worker pool / resource-ledger consistency."""
    violations = []
    nid = raylet.node_id
    seen_workers = {}
    for lease_id, handle in raylet.leases.items():
        if handle.lease_id != lease_id:
            violations.append(
                Violation(
                    "lease-exactly-once",
                    nid,
                    f"lease {lease_id[:12]} maps to handle tagged "
                    f"{str(handle.lease_id)[:12]}",
                )
            )
        if handle.worker_id in seen_workers:
            violations.append(
                Violation(
                    "lease-exactly-once",
                    nid,
                    f"worker {handle.worker_id[:12]} held by two leases "
                    f"({seen_workers[handle.worker_id][:12]}, {lease_id[:12]})",
                )
            )
        seen_workers[handle.worker_id] = lease_id
        if handle.worker_id not in raylet.workers:
            violations.append(
                Violation(
                    "lease-exactly-once",
                    nid,
                    f"lease {lease_id[:12]} holds unknown (dead?) worker "
                    f"{handle.worker_id[:12]} — leaked grant",
                )
            )
        if handle in raylet.idle_workers:
            violations.append(
                Violation(
                    "lease-exactly-once",
                    nid,
                    f"worker {handle.worker_id[:12]} both leased and idle",
                )
            )
    if len(raylet.idle_workers) != len(set(map(id, raylet.idle_workers))):
        violations.append(
            Violation("lease-exactly-once", nid, "duplicate idle pool entry")
        )
    if not raylet.available.nonnegative():
        violations.append(
            Violation(
                "resource-ledger",
                nid,
                f"negative availability {raylet.available.to_dict()}",
            )
        )
    if not raylet.pg_committed and not raylet.pg_prepared:
        # Without placement groups mutating totals the ledger must balance
        # exactly: total == available + sum of leased demands.
        ledger = raylet.available
        for handle in raylet.leases.values():
            if handle.demand is not None:
                ledger = ledger + handle.demand
        if ledger != raylet.total:
            violations.append(
                Violation(
                    "resource-ledger",
                    nid,
                    f"total {raylet.total.to_dict()} != available+leased "
                    f"{ledger.to_dict()} (leaked or double-counted grant)",
                )
            )
    return violations


def check_actors(gcs_server) -> List[Violation]:
    violations = []
    for aid, actor in gcs_server.actors.items():
        if actor.state not in TERMINAL_ACTOR_STATES:
            violations.append(
                Violation(
                    "actors-terminal",
                    "gcs",
                    f"actor {aid[:12]} stuck in {actor.state}",
                )
            )
    return violations


def check_store(raylet) -> List[Violation]:
    violations = []
    if raylet.push_assembly:
        violations.append(
            Violation(
                "store-settled",
                raylet.node_id,
                f"unsealed push assemblies {sorted(raylet.push_assembly)}",
            )
        )
    if raylet.restoring:
        violations.append(
            Violation(
                "store-settled",
                raylet.node_id,
                f"in-flight restores {sorted(raylet.restoring)}",
            )
        )
    return violations


def check_no_data_loss(ray_mod, ledger, timeout_s: float = 120.0) -> List[Violation]:
    """Every acknowledged object — a driver ``put`` or a task return whose
    readiness the workload observed — with a still-live ref must resolve to
    its exact bytes after convergence (restored from external storage or
    re-executed from lineage), or fail with the typed
    ``ObjectReconstructionFailedError``. Wrong bytes, hangs (a get timeout),
    and untyped errors are data loss.

    A functional probe in the objects-reconstructable mold: the runner
    passes the ``(ref, sha256-hexdigest, kind)`` ledger it built while the
    workload ran. Runs on the driver thread (blocking gets), not the
    cluster loop.
    """
    import hashlib

    from ray_tpu._private.common import ObjectReconstructionFailedError

    violations = []
    for ref, digest, kind in ledger:
        try:
            data = ray_mod.get(ref, timeout=timeout_s)
        except ObjectReconstructionFailedError:
            # Principled, typed loss (lineage pruned / unreconstructable by
            # design): the caller knows exactly what happened and why.
            continue
        except Exception as e:
            violations.append(
                Violation(
                    "no-data-loss",
                    "-",
                    f"{kind} object {ref.hex()[:12]} irrecoverable with "
                    f"untyped {type(e).__name__}: {e}",
                )
            )
            continue
        if hashlib.sha256(data).hexdigest() != digest:
            violations.append(
                Violation(
                    "no-data-loss",
                    "-",
                    f"{kind} object {ref.hex()[:12]} resolved to wrong bytes",
                )
            )
    return violations


async def check_orphan_tasks(settle_s: float = 1.0) -> List[Violation]:
    """Transient coroutines still pending across two spaced snapshots are
    orphans (a _grant that never resolved, a push wedged on a dead link).
    Daemon loops and RPC dispatch of long-poll handlers are exempt."""

    def _transients():
        out = set()
        for task in rpc._BG_TASKS:
            if task.done():
                continue
            coro = task.get_coro()
            qual = getattr(coro, "__qualname__", "")
            if qual in TRANSIENT_QUALNAMES:
                out.add(task)
        return out

    first = _transients()
    if not first:
        return []
    await asyncio.sleep(settle_s)
    stuck = [t for t in first if t in _transients()]
    return [
        Violation(
            "no-orphaned-tasks",
            "-",
            f"{getattr(t.get_coro(), '__qualname__', '?')} pending "
            f">{settle_s}s after quiescence",
        )
        for t in stuck
    ]


def check_deadlines(gcs_server=None) -> List[Violation]:
    """No call outlives its deadline: every handler dispatched under a wire
    deadline must finish — or unwind its cancellation — within the grace
    period (``config.rpc_deadline_grace_s``) of it. An overrun means a
    handler swallowed cancellation or the loop stalled long enough that
    shedding/enforcement never got to run; either way a hop kept working
    after its caller gave up.

    Two sources: the driver-process counters (rpc.deadline_stats, reset per
    seed by the runner) and — when a GCS server is given — the cluster
    aggregate fed by worker-subprocess flushes (ReportDeadlineStats), so a
    replica or task worker that outlived its deadline is a violation too."""
    violations = [
        Violation(
            "no-call-outlives-deadline",
            "-",
            f"handler {method} finished {late:.3f}s past its wire deadline "
            "(> grace period)",
        )
        for method, late in rpc.deadline_stats.overruns
    ]
    if gcs_server is not None:
        for wid, method, late in gcs_server.worker_deadline_stats["overruns"]:
            violations.append(
                Violation(
                    "no-call-outlives-deadline",
                    str(wid),
                    f"worker handler {method} finished {late:.3f}s past its "
                    "wire deadline (> grace period)",
                )
            )
    return violations


async def check(cluster) -> List[Violation]:
    """Run every invariant against a quiesced cluster."""
    violations: List[Violation] = []
    for raylet in list(cluster.raylets.values()):
        violations.extend(check_leases(raylet))
        violations.extend(check_store(raylet))
    if cluster.gcs_server is not None:
        violations.extend(check_actors(cluster.gcs_server))
    violations.extend(await check_orphan_tasks())
    violations.extend(check_deadlines(cluster.gcs_server))
    return violations
