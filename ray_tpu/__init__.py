"""ray_tpu: a TPU-native distributed compute framework.

Tasks, actors, and shared-memory objects with ownership-based reference
counting (the reference architecture of dream3d-ai/ray, rebuilt TPU-first),
plus ML libraries — train/tune/data/serve/rl — built on JAX/XLA/Pallas where
collectives lower to `jax.lax` ops over ICI inside compiled SPMD programs.

Public core API (analog of python/ray/_private/worker.py exports):

    import ray_tpu

    ray_tpu.init()

    @ray_tpu.remote
    def f(x):
        return x * 2

    ray_tpu.get(f.remote(2))  # 4
"""

from ray_tpu._private.common import (
    ActorDiedError,
    ActorUnavailableError,
    GetTimeoutError,
    ObjectLostError,
    ObjectReconstructionFailedError,
    PlacementGroupError,
    RayTpuError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from ray_tpu._private.core_worker import ObjectRef, ObjectRefGenerator
from ray_tpu._private.worker import (
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    shutdown,
    wait,
)
from ray_tpu.actor import ActorClass, ActorHandle
from ray_tpu.remote_function import RemoteFunction

__version__ = "0.1.0"


def remote(*args, **kwargs):
    """Decorator turning a function into a RemoteFunction or a class into an
    ActorClass. Usable bare (`@remote`) or with options
    (`@remote(num_cpus=2, num_tpus=1)`)."""

    def decorate(obj):
        if isinstance(obj, type):
            return ActorClass(obj, **kwargs)
        return RemoteFunction(obj, **kwargs)

    if len(args) == 1 and not kwargs and (callable(args[0]) or isinstance(args[0], type)):
        return decorate(args[0])
    if args:
        raise TypeError("remote() takes keyword options only, e.g. @remote(num_cpus=2)")
    return decorate


def method(**kwargs):
    """Decorator for actor methods to set defaults (e.g. num_returns)."""

    def deco(fn):
        fn._method_options = kwargs
        return fn

    return deco


__all__ = [
    "init",
    "shutdown",
    "remote",
    "method",
    "get",
    "put",
    "wait",
    "cancel",
    "kill",
    "ObjectRefGenerator",
    "get_actor",
    "nodes",
    "cluster_resources",
    "available_resources",
    "is_initialized",
    "ObjectRef",
    "ActorClass",
    "ActorHandle",
    "RemoteFunction",
    "RayTpuError",
    "TaskError",
    "ActorDiedError",
    "ActorUnavailableError",
    "WorkerCrashedError",
    "ObjectLostError",
    "ObjectReconstructionFailedError",
    "GetTimeoutError",
    "TaskCancelledError",
    "PlacementGroupError",
]
