"""ray_tpu.runtime_env: per-task/actor execution environments.

Analog of python/ray/runtime_env + python/ray/_private/runtime_env plugins:
  - env_vars: exported into the executing worker
  - working_dir: local directory zipped, shipped via GCS KV, extracted on
    the executing node, chdir'd + sys.path'd (reference: working_dir.py)
  - py_modules: list of module dirs shipped the same way (py_modules.py)
  - pip / conda: accepted and validated for API parity; installation is a
    no-op in air-gapped deployments (logged) — the reference shells out to
    pip/conda from its runtime-env agent.

Preparation (upload) runs in the submitting process; application runs in the
worker before user code executes — permanently for actors (dedicated
process), scoped for tasks.
"""

from ray_tpu.runtime_env.context import RuntimeEnv, apply_runtime_env, prepare

__all__ = ["RuntimeEnv", "apply_runtime_env", "prepare"]
