"""Runtime-env preparation (driver) and application (worker)."""

from __future__ import annotations

import contextlib
import hashlib
import io
import logging
import os
import sys
import zipfile
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

KV_NS = "_runtime_env"
EXTRACT_ROOT = "/tmp/ray_tpu_runtime_env"
MAX_PACKAGE_BYTES = 100 * 1024 * 1024
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


class RuntimeEnv(dict):
    """Dict subclass for API parity with ray.runtime_env.RuntimeEnv."""

    KNOWN = {
        "env_vars", "working_dir", "py_modules", "pip", "conda",
        "container", "config",
    }

    def __init__(self, **kwargs):
        unknown = set(kwargs) - self.KNOWN
        if unknown:
            raise ValueError(f"unknown runtime_env fields: {unknown}")
        super().__init__(**{k: v for k, v in kwargs.items() if v is not None})


async def _rmtree_async(path: str) -> None:
    """Delete a tree off the event loop: half-built pip/conda envs can be
    hundreds of MB, and a sync rmtree there stalls every heartbeat the
    hosting loop owes while the unlink storm runs."""
    import asyncio
    import functools
    import shutil

    await asyncio.get_running_loop().run_in_executor(
        None, functools.partial(shutil.rmtree, path, ignore_errors=True)
    )


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
            for fname in files:
                full = os.path.join(root, fname)
                zf.write(full, os.path.relpath(full, path))
    data = buf.getvalue()
    if len(data) > MAX_PACKAGE_BYTES:
        raise ValueError(
            f"runtime_env package {path!r} is {len(data)} bytes "
            f"(limit {MAX_PACKAGE_BYTES})"
        )
    return data


async def _upload_dir(core, path: str) -> str:
    """Zip + dedupe-upload a directory; returns the KV key."""
    path = os.path.abspath(os.path.expanduser(path))
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env directory {path!r} does not exist")
    data = _zip_dir(path)
    key = f"pkg_{hashlib.sha1(data).hexdigest()[:20]}"
    if not await core.gcs.kv_exists(key, ns=KV_NS):
        await core.gcs.kv_put(key, data, ns=KV_NS)
    return key


async def prepare(core, runtime_env: Optional[Dict[str, Any]]) -> Optional[Dict]:
    """Driver-side: replace local paths with uploaded package keys
    (reference: runtime-env URIs pinned in the GCS)."""
    if not runtime_env:
        return runtime_env
    env = dict(runtime_env)
    wd = env.get("working_dir")
    if wd and not str(wd).startswith("pkg_"):
        env["working_dir"] = await _upload_dir(core, wd)
    mods = env.get("py_modules")
    if mods:
        uploaded = []
        for m in mods:
            uploaded.append(
                m if str(m).startswith("pkg_") else await _upload_dir(core, m)
            )
        env["py_modules"] = uploaded
    if env.get("pip"):
        env["pip"] = _normalize_pip(env["pip"])
    if env.get("conda"):
        env["conda"] = _normalize_conda(env["conda"])
    if env.get("container"):
        spec = env["container"]
        if not isinstance(spec, dict) or not spec.get("image"):
            raise ValueError(
                "runtime_env container spec must be a dict with an 'image'"
            )
    return env


def _normalize_pip(pip: Any) -> Dict[str, Any]:
    """Driver-side pip-field normalization (reference: runtime_env/pip.py
    accepts a list, a requirements path, or a dict)."""
    if isinstance(pip, str):  # requirements.txt path, read driver-side
        with open(os.path.expanduser(pip)) as f:
            packages = [
                ln.strip()
                for ln in f
                if ln.strip() and not ln.strip().startswith("#")
            ]
        return {"packages": packages}
    if isinstance(pip, (list, tuple)):
        return {"packages": [str(p) for p in pip]}
    if isinstance(pip, dict):
        out = {"packages": [str(p) for p in pip.get("packages") or []]}
        if pip.get("pip_check") is not None:
            out["pip_check"] = bool(pip["pip_check"])
        if pip.get("pip_install_options"):
            out["pip_install_options"] = [str(o) for o in pip["pip_install_options"]]
        return out
    raise ValueError(f"unsupported runtime_env pip spec: {pip!r}")


async def _fetch_package(core, key: str) -> str:
    """Worker-side: download + extract a package once; returns its path."""
    dest = os.path.join(EXTRACT_ROOT, key)
    if os.path.isdir(dest):
        return dest
    blob = await core.gcs.kv_get(key, ns=KV_NS)
    if blob is None:
        raise RuntimeError(f"runtime_env package {key} missing from GCS")
    tmp = dest + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        zf.extractall(tmp)
    try:
        os.rename(tmp, dest)
    except OSError:  # concurrent extraction won the race
        await _rmtree_async(tmp)
    return dest


async def apply_runtime_env(
    core, runtime_env: Optional[Dict[str, Any]], *, chdir: bool = True
) -> None:
    """Worker-side application. Actors (dedicated process) use chdir=True;
    tasks in shared workers pass chdir=False (sys.path only)."""
    if not runtime_env:
        return
    for k, v in (runtime_env.get("env_vars") or {}).items():
        os.environ[k] = str(v)
    wd = runtime_env.get("working_dir")
    if wd:
        path = await _fetch_package(core, wd)
        if chdir:
            os.chdir(path)
        if path not in sys.path:
            sys.path.insert(0, path)
    for key in runtime_env.get("py_modules") or []:
        path = await _fetch_package(core, key)
        if path not in sys.path:
            sys.path.insert(0, path)
    pip = runtime_env.get("pip")
    if pip:
        site = await ensure_pip_env(pip)
        if site:
            _activate_pip_site(site)
    conda = runtime_env.get("conda")
    if conda:
        prefix = await ensure_conda_env(conda)
        if prefix:
            _activate_conda_env(prefix)


def _pip_env_key(spec: Dict[str, Any]) -> str:
    import json

    blob = json.dumps(spec, sort_keys=True).encode()
    return hashlib.sha1(blob).hexdigest()[:20]


def _site_packages(venv_dir: str) -> str:
    return os.path.join(
        venv_dir,
        "lib",
        f"python{sys.version_info.major}.{sys.version_info.minor}",
        "site-packages",
    )


# The pip site-packages dir currently active on this worker (shared task
# workers run different envs over time; see _activate_pip_site).
_active_pip_site: Optional[str] = None


def _activate_pip_site(site: str) -> None:
    """Switch this worker process to ``site``'s pip env. Sequential tasks
    with different pip specs must each see exactly their own packages: the
    previous env's path entry is removed and every module imported from it
    is evicted from sys.modules, so the next import resolves against the
    new env rather than the stale module cache (the silent-wrong-version
    hazard of sharing workers across envs)."""
    global _active_pip_site
    if _active_pip_site == site:
        return
    old = _active_pip_site
    if old is not None:
        try:
            sys.path.remove(old)
        except ValueError:
            pass
        for name, mod in list(sys.modules.items()):
            f = getattr(mod, "__file__", None)
            if f and f.startswith(old + os.sep):
                del sys.modules[name]
    if site not in sys.path:
        sys.path.insert(0, site)
    _active_pip_site = site


async def ensure_pip_env(pip: Any) -> Optional[str]:
    """Worker-side: build (or reuse) a venv for the pip spec; returns its
    site-packages path, or None for an empty spec (reference:
    runtime_env/pip.py PipProcessor — per-hash cached virtualenv with
    system-site-packages so the image's baked-in deps stay importable).

    Concurrency protocol: an exclusive flock on a sidecar lock file elects
    one installer at a time; the kernel releases the lock if the holder
    dies mid-install (no staleness heuristics, no TOCTOU). Whoever acquires
    the lock re-checks the ready marker first, so waiters either reuse the
    finished env or retry the install and surface the real error
    themselves. Failures raise — never silently run without the requested
    packages."""
    import asyncio
    import fcntl

    spec = _normalize_pip(pip)
    if not spec.get("packages"):
        return None
    key = _pip_env_key(spec)
    dest = os.path.join(EXTRACT_ROOT, "pip", key)
    marker = os.path.join(dest, ".ready")
    if os.path.exists(marker):
        return _site_packages(dest)
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    # Cold path: one-time env materialization; the flock itself is taken
    # via run_in_executor below, only the tiny lock-file open is sync.
    lock_f = open(dest + ".flock", "a+")  # aio-lint: disable=blocking-call
    try:
        await asyncio.get_running_loop().run_in_executor(
            None, fcntl.flock, lock_f, fcntl.LOCK_EX
        )
        if os.path.exists(marker):  # another installer finished while we waited
            return _site_packages(dest)

        async def _run(cmd, what):
            proc = await asyncio.create_subprocess_exec(
                *cmd,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.STDOUT,
            )
            out, _ = await proc.communicate()
            if proc.returncode != 0:
                raise RuntimeError(f"{what} failed: {out.decode()[-2000:]}")

        try:
            await _rmtree_async(dest)  # half-built leftovers
            await _run(
                [sys.executable, "-m", "venv", "--system-site-packages", dest],
                "venv creation",
            )
            cmd = [
                os.path.join(dest, "bin", "python"), "-m", "pip", "install",
                "--disable-pip-version-check",
            ]
            cmd += spec.get("pip_install_options") or []
            cmd += spec["packages"]
            await _run(cmd, f"pip install of {spec['packages']}")
            if spec.get("pip_check"):
                await _run(
                    [os.path.join(dest, "bin", "python"), "-m", "pip", "check"],
                    "pip check",
                )
            with open(marker, "w") as f:  # aio-lint: disable=blocking-call
                f.write("ok")
            return _site_packages(dest)
        except BaseException:
            import shutil

            # Cancellation path: an await here could itself be interrupted by
            # a second cancel and skip the cleanup, leaving a half-built env
            # that later lookups would mistake for ready. Stay synchronous.
            shutil.rmtree(dest, ignore_errors=True)  # aio-lint: disable=blocking-call
            raise
    finally:
        try:
            fcntl.flock(lock_f, fcntl.LOCK_UN)
        except OSError:
            pass
        lock_f.close()


# -- conda (reference: runtime_env/conda.py) ---------------------------------


def _normalize_conda(conda: Any) -> Dict[str, Any]:
    """Accepts a named env (str), an environment.yml path (str ending in
    .yml/.yaml, read driver-side), or an inline spec dict."""
    if isinstance(conda, str):
        if conda.endswith((".yml", ".yaml")):
            import yaml

            with open(os.path.expanduser(conda)) as f:
                spec = yaml.safe_load(f) or {}
            if not isinstance(spec, dict):
                raise ValueError(f"conda yaml {conda!r} is not a mapping")
            return spec
        return {"name": conda, "_existing": True}
    if isinstance(conda, dict):
        return dict(conda)
    raise ValueError(f"unsupported runtime_env conda spec: {conda!r}")


def _conda_site_packages(prefix: str) -> str:
    import glob

    hits = glob.glob(os.path.join(prefix, "lib", "python*", "site-packages"))
    return hits[0] if hits else os.path.join(
        prefix, "lib",
        f"python{sys.version_info.major}.{sys.version_info.minor}",
        "site-packages",
    )


_active_conda_prefix: Optional[str] = None


def _activate_conda_env(prefix: str) -> None:
    """Switch this worker to the conda env: its site-packages goes on
    sys.path (with the previous env's modules evicted, mirroring
    _activate_pip_site) and CONDA_PREFIX/PATH point at it so subprocesses
    see the env too."""
    global _active_conda_prefix
    if _active_conda_prefix == prefix:
        return
    old = _active_conda_prefix
    if old is not None:
        old_site = _conda_site_packages(old)
        try:
            sys.path.remove(old_site)
        except ValueError:
            pass
        for name, mod in list(sys.modules.items()):
            f = getattr(mod, "__file__", None)
            if f and f.startswith(old_site + os.sep):
                del sys.modules[name]
    site = _conda_site_packages(prefix)
    if site not in sys.path:
        sys.path.insert(0, site)
    os.environ["CONDA_PREFIX"] = prefix
    bindir = os.path.join(prefix, "bin")
    if bindir not in os.environ.get("PATH", "").split(os.pathsep):
        os.environ["PATH"] = bindir + os.pathsep + os.environ.get("PATH", "")
    _active_conda_prefix = prefix


def _conda_env_key(spec: Dict[str, Any]) -> str:
    import json

    blob = json.dumps(spec, sort_keys=True).encode()
    return hashlib.sha1(blob).hexdigest()[:20]


async def ensure_conda_env(conda: Any) -> Optional[str]:
    """Worker-side: provision (or reuse) the conda env; returns its prefix.

    Named existing envs resolve through `conda run`; spec dicts create a
    per-hash cached env with `conda env create -p <prefix> -f <yaml>` under
    the same flock install-election protocol as pip envs (reference:
    runtime_env/conda.py per-hash cached envs). The conda binary comes from
    PATH — tests inject a shim, like the GCE provider's fake gcloud."""
    import asyncio
    import fcntl

    spec = _normalize_conda(conda)

    async def _run(cmd, what):
        proc = await asyncio.create_subprocess_exec(
            *cmd,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT,
        )
        out, _ = await proc.communicate()
        if proc.returncode != 0:
            raise RuntimeError(f"{what} failed: {out.decode()[-2000:]}")
        return out.decode()

    if spec.get("_existing"):
        out = await _run(
            [
                "conda", "run", "-n", spec["name"], "python", "-c",
                "import sys; print(sys.prefix)",
            ],
            f"conda env lookup of {spec['name']!r}",
        )
        prefix = out.strip().splitlines()[-1]
        return prefix

    key = _conda_env_key(spec)
    dest = os.path.join(EXTRACT_ROOT, "conda", key)
    marker = os.path.join(dest, ".ready")
    if os.path.exists(marker):
        return dest
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    # Cold path: one-time env materialization; the flock itself is taken
    # via run_in_executor below, only the tiny lock-file open is sync.
    lock_f = open(dest + ".flock", "a+")  # aio-lint: disable=blocking-call
    try:
        await asyncio.get_running_loop().run_in_executor(
            None, fcntl.flock, lock_f, fcntl.LOCK_EX
        )
        if os.path.exists(marker):  # another installer finished meanwhile
            return dest
        try:
            import json as _json
            import tempfile

            await _rmtree_async(dest)
            yml = {k: v for k, v in spec.items() if not k.startswith("_")}
            with tempfile.NamedTemporaryFile(
                "w", suffix=".yml", delete=False
            ) as f:
                # JSON is valid YAML; no yaml dependency needed worker-side.
                _json.dump(yml, f)
                yml_path = f.name
            try:
                await _run(
                    ["conda", "env", "create", "-p", dest, "-f", yml_path],
                    f"conda env create for {yml}",
                )
            finally:
                os.unlink(yml_path)
            with open(marker, "w") as f:  # aio-lint: disable=blocking-call
                f.write("ok")
            return dest
        except BaseException:
            import shutil

            # Cancellation path: an await here could itself be interrupted by
            # a second cancel and skip the cleanup, leaving a half-built env
            # that later lookups would mistake for ready. Stay synchronous.
            shutil.rmtree(dest, ignore_errors=True)  # aio-lint: disable=blocking-call
            raise
    finally:
        try:
            fcntl.flock(lock_f, fcntl.LOCK_UN)
        except OSError:
            pass
        lock_f.close()


@contextlib.contextmanager
def scoped_env_vars(env_vars: Optional[Dict[str, str]]):
    """Task-scoped env vars: set for the call, restored after (tasks share
    their worker process, unlike actors)."""
    if not env_vars:
        yield
        return
    saved: Dict[str, Optional[str]] = {}
    for k, v in env_vars.items():
        saved[k] = os.environ.get(k)
        os.environ[k] = str(v)
    try:
        yield
    finally:
        for k, old in saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
