"""Runtime-env preparation (driver) and application (worker)."""

from __future__ import annotations

import contextlib
import hashlib
import io
import logging
import os
import sys
import zipfile
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

KV_NS = "_runtime_env"
EXTRACT_ROOT = "/tmp/ray_tpu_runtime_env"
MAX_PACKAGE_BYTES = 100 * 1024 * 1024
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


class RuntimeEnv(dict):
    """Dict subclass for API parity with ray.runtime_env.RuntimeEnv."""

    KNOWN = {"env_vars", "working_dir", "py_modules", "pip", "conda", "config"}

    def __init__(self, **kwargs):
        unknown = set(kwargs) - self.KNOWN
        if unknown:
            raise ValueError(f"unknown runtime_env fields: {unknown}")
        super().__init__(**{k: v for k, v in kwargs.items() if v is not None})


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
            for fname in files:
                full = os.path.join(root, fname)
                zf.write(full, os.path.relpath(full, path))
    data = buf.getvalue()
    if len(data) > MAX_PACKAGE_BYTES:
        raise ValueError(
            f"runtime_env package {path!r} is {len(data)} bytes "
            f"(limit {MAX_PACKAGE_BYTES})"
        )
    return data


async def _upload_dir(core, path: str) -> str:
    """Zip + dedupe-upload a directory; returns the KV key."""
    path = os.path.abspath(os.path.expanduser(path))
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env directory {path!r} does not exist")
    data = _zip_dir(path)
    key = f"pkg_{hashlib.sha1(data).hexdigest()[:20]}"
    if not await core.gcs.kv_exists(key, ns=KV_NS):
        await core.gcs.kv_put(key, data, ns=KV_NS)
    return key


async def prepare(core, runtime_env: Optional[Dict[str, Any]]) -> Optional[Dict]:
    """Driver-side: replace local paths with uploaded package keys
    (reference: runtime-env URIs pinned in the GCS)."""
    if not runtime_env:
        return runtime_env
    env = dict(runtime_env)
    wd = env.get("working_dir")
    if wd and not str(wd).startswith("pkg_"):
        env["working_dir"] = await _upload_dir(core, wd)
    mods = env.get("py_modules")
    if mods:
        uploaded = []
        for m in mods:
            uploaded.append(
                m if str(m).startswith("pkg_") else await _upload_dir(core, m)
            )
        env["py_modules"] = uploaded
    if env.get("pip") or env.get("conda"):
        logger.warning(
            "runtime_env pip/conda requested but package installation is "
            "disabled in this deployment; dependencies must be baked into "
            "the image"
        )
    return env


async def _fetch_package(core, key: str) -> str:
    """Worker-side: download + extract a package once; returns its path."""
    dest = os.path.join(EXTRACT_ROOT, key)
    if os.path.isdir(dest):
        return dest
    blob = await core.gcs.kv_get(key, ns=KV_NS)
    if blob is None:
        raise RuntimeError(f"runtime_env package {key} missing from GCS")
    tmp = dest + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        zf.extractall(tmp)
    try:
        os.rename(tmp, dest)
    except OSError:  # concurrent extraction won the race
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return dest


async def apply_runtime_env(
    core, runtime_env: Optional[Dict[str, Any]], *, chdir: bool = True
) -> None:
    """Worker-side application. Actors (dedicated process) use chdir=True;
    tasks in shared workers pass chdir=False (sys.path only)."""
    if not runtime_env:
        return
    for k, v in (runtime_env.get("env_vars") or {}).items():
        os.environ[k] = str(v)
    wd = runtime_env.get("working_dir")
    if wd:
        path = await _fetch_package(core, wd)
        if chdir:
            os.chdir(path)
        if path not in sys.path:
            sys.path.insert(0, path)
    for key in runtime_env.get("py_modules") or []:
        path = await _fetch_package(core, key)
        if path not in sys.path:
            sys.path.insert(0, path)


@contextlib.contextmanager
def scoped_env_vars(env_vars: Optional[Dict[str, str]]):
    """Task-scoped env vars: set for the call, restored after (tasks share
    their worker process, unlike actors)."""
    if not env_vars:
        yield
        return
    saved: Dict[str, Optional[str]] = {}
    for k, v in env_vars.items():
        saved[k] = os.environ.get(k)
        os.environ[k] = str(v)
    try:
        yield
    finally:
        for k, old in saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
