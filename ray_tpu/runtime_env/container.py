"""Container runtime-env: run a worker inside podman/docker (reference:
python/ray/_private/runtime_env/container.py — the worker command is
wrapped in a `podman run` argv; the container shares the host network so
raylet/GCS/object-store TCP endpoints keep working).

Scope: actors own their process, so `runtime_env={"container": {...}}` on
an actor makes the raylet spawn THAT actor's worker inside the container
(tasks in shared pool workers cannot switch containers mid-process; the
reference has the same per-worker granularity).

The container runtime binary is discovered on PATH (podman preferred,
docker fallback) — tests put a fake `podman` shim first on PATH, exactly
like the GCE provider's injectable gcloud runner.
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Dict, List, Optional


def find_container_runtime() -> Optional[str]:
    for binary in ("podman", "docker"):
        path = shutil.which(binary)
        if path:
            return path
    return None


def build_container_argv(
    spec: Dict[str, Any],
    inner_argv: List[str],
    env: Dict[str, str],
    runtime: Optional[str] = None,
) -> List[str]:
    """The full argv that boots `inner_argv` inside the requested image.

    spec: {"image": str, "run_options": [str, ...], "worker_path": str?}
      - image: required container image.
      - run_options: extra args spliced into `run` (mounts, --gpus, ...).
      - worker_path: python inside the image (default: python3).
    env vars are passed through with --env so the worker finds its raylet,
    GCS, session, and IDs; --network=host keeps every TCP endpoint valid.
    """
    image = spec.get("image")
    if not image:
        raise ValueError("runtime_env container spec needs an 'image'")
    runtime = runtime or find_container_runtime()
    if runtime is None:
        raise RuntimeError(
            "runtime_env container requested but neither podman nor docker "
            "is on PATH"
        )
    argv = [
        runtime,
        "run",
        "--rm",
        "--network=host",
        # The shm object store is host-shared memory: the worker must see
        # the same /dev/shm to map plasma segments zero-copy.
        "-v", "/dev/shm:/dev/shm",
        "-v", "/tmp:/tmp",
    ]
    for k, v in env.items():
        argv += ["--env", f"{k}={v}"]
    argv += list(spec.get("run_options") or [])
    argv.append(str(image))
    python = spec.get("worker_path", "python3")
    # inner_argv is [sys.executable, "-m", "ray_tpu._private.worker_main"];
    # inside the image the interpreter is the image's python.
    argv += [python] + list(inner_argv[1:])
    return argv
