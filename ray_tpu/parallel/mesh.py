"""Device-mesh construction and sharding-rule helpers.

The TPU-native replacement for the reference's process-group bootstrap
(python/ray/train/torch/config.py:65-147 builds NCCL groups; here parallelism
is expressed as axes of one jax.sharding.Mesh and XLA inserts the collectives
over ICI). Canonical axis names follow the scaling-book convention:

    data      — pure data parallelism (gradient psum)
    fsdp      — data parallelism with sharded params/optimizer (ZeRO-3)
    tensor    — megatron-style tensor parallelism within attention/mlp
    sequence  — context parallelism (ring attention / all-to-all)
    expert    — MoE expert parallelism

Any subset may be present; size-1 axes are free, so one codepath serves
single-chip through multi-pod.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

DATA, FSDP, TENSOR, SEQUENCE, EXPERT = "data", "fsdp", "tensor", "sequence", "expert"
CANONICAL_ORDER = (DATA, FSDP, EXPERT, SEQUENCE, TENSOR)


@dataclass
class MeshSpec:
    """Declarative mesh: axis name -> size. One axis may be -1 (inferred).

    Axis order matters on hardware: later axes are placed on
    faster/closer device groups (tensor innermost => tensor-parallel
    collectives ride the shortest ICI hops).
    """

    axes: Dict[str, int] = field(default_factory=dict)

    def resolve(self, num_devices: int) -> Dict[str, int]:
        axes = dict(self.axes)
        unknown = [k for k, v in axes.items() if v == -1]
        if len(unknown) > 1:
            raise ValueError("at most one mesh axis may be -1")
        known = math.prod(v for v in axes.values() if v != -1)
        if unknown:
            if num_devices % known:
                raise ValueError(
                    f"cannot infer {unknown[0]}: {num_devices} % {known} != 0"
                )
            axes[unknown[0]] = num_devices // known
        if math.prod(axes.values()) != num_devices:
            raise ValueError(
                f"mesh {axes} does not cover {num_devices} devices"
            )
        return axes


def make_mesh(
    axes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence] = None,
):
    """Build a jax.sharding.Mesh from an axis spec over the given devices
    (defaults to all). `axes=None` -> pure data-parallel mesh.

    Canonical axes (data/fsdp/expert/sequence/tensor) are ALWAYS laid out in
    CANONICAL_ORDER regardless of dict order, so tensor/sequence collectives
    ride the innermost (fastest) ICI groups; non-canonical axis names keep
    their given order, outermost. Pass a pre-shaped `jax.sharding.Mesh`
    directly to downstream APIs if full manual control over device placement
    is needed."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if axes is None:
        axes = {DATA: len(devices)}
    resolved = MeshSpec(dict(axes)).resolve(len(devices))
    # Canonical placement: known axes ordered so tensor/sequence land
    # innermost (fastest ICI); unknown axes keep user order, outermost.
    resolved = dict(sorted(
        resolved.items(),
        key=lambda kv: CANONICAL_ORDER.index(kv[0])
        if kv[0] in CANONICAL_ORDER else -1,
    ))
    names = tuple(resolved.keys())
    shape = tuple(resolved.values())
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, names)


def data_parallel_spec(mesh) -> "jax.sharding.PartitionSpec":  # noqa: F821
    from jax.sharding import PartitionSpec as P

    batch_axes = [a for a in (DATA, FSDP) if a in mesh.axis_names]
    return P(tuple(batch_axes) if batch_axes else None)


def batch_sharding(mesh):
    """NamedSharding for a [batch, ...] input: batch split over data-like axes."""
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, data_parallel_spec(mesh))


# ---------------------------------------------------------------------------
# Logical-axis sharding rules (t5x/flax style): map parameter pytree paths to
# PartitionSpecs by matching logical axis names.
# ---------------------------------------------------------------------------


@dataclass
class ShardingRules:
    """Rules mapping logical array axes to mesh axes.

    e.g. rules = ShardingRules({"embed": "fsdp", "mlp": "tensor",
                                "heads": "tensor", "batch": ("data", "fsdp")})
    """

    rules: Dict[str, Optional[object]] = field(default_factory=dict)

    def spec(self, logical_axes: Sequence[Optional[str]]):
        from jax.sharding import PartitionSpec as P

        return P(*(self.rules.get(a) if a else None for a in logical_axes))

    def sharding(self, mesh, logical_axes: Sequence[Optional[str]]):
        from jax.sharding import NamedSharding

        return NamedSharding(mesh, self.spec(logical_axes))


# Default rules for transformer-family models: params shard over fsdp+tensor,
# activations over data+sequence.
def default_transformer_rules(mesh) -> ShardingRules:
    names = mesh.axis_names
    has = lambda a: a in names

    def ax(*prefs):
        got = [p for p in prefs if has(p)]
        if not got:
            return None
        return got[0] if len(got) == 1 else tuple(got)

    return ShardingRules(
        {
            "batch": ax(DATA, FSDP),
            "embed": ax(FSDP),
            "mlp": ax(TENSOR),
            "heads": ax(TENSOR),
            "kv": None,
            "vocab": ax(TENSOR),
            "seq": ax(SEQUENCE),
        }
    )


def shard_pytree(tree, mesh, spec_fn):
    """device_put every leaf with the sharding from spec_fn(path, leaf)."""
    import jax

    def place(path, leaf):
        return jax.device_put(leaf, spec_fn(path, leaf))

    return jax.tree_util.tree_map_with_path(place, tree)


def fsdp_sharding_for_leaf(mesh, leaf):
    """Default ZeRO-3 rule: shard the largest divisible axis over fsdp."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if FSDP not in mesh.axis_names:
        return NamedSharding(mesh, P())
    n = mesh.shape[FSDP]
    shape = getattr(leaf, "shape", ())
    if not shape:
        return NamedSharding(mesh, P())
    # Largest axis divisible by the fsdp size, preferring the first.
    candidates = [i for i, d in enumerate(shape) if d % n == 0 and d >= n]
    if not candidates:
        return NamedSharding(mesh, P())
    axis = max(candidates, key=lambda i: shape[i])
    spec = [None] * len(shape)
    spec[axis] = FSDP
    return NamedSharding(mesh, P(*spec))


def host_local_device_count() -> int:
    import jax

    return jax.local_device_count()
