"""Ring attention: exact attention over a sequence-sharded axis.

Long-context capability the reference delegates to integrations (SURVEY §5:
Ray itself ships none; vLLM/DeepSpeed examples provide it). Here it is a
first-class primitive: K/V blocks rotate around the `sequence` mesh axis via
`ppermute` while each device keeps its Q shard, accumulating exact softmax
attention with the online (flash-style) max/sum recurrence. Communication
rides ICI neighbor hops — the canonical TPU pattern.

Layout inside shard_map: q, k, v are local shards [B, T_local, H, D] where the
global sequence is sharded over `axis_name` (N devices). Differentiable
(scan + ppermute both have transpose rules); wrap the caller in
jax.checkpoint to trade recompute for memory on long sequences.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

_BIG_NEG = -1e30


def _shard_map():
    """jax.shard_map graduated from jax.experimental between minor releases;
    resolve whichever this jax ships."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm
    from jax.experimental.shard_map import shard_map as sm

    return sm


def _pvary(x, axes):
    """jax.lax.pvary only exists on jax versions with varying-axes type
    checking; older releases don't track varying axes, so identity is
    exactly equivalent there."""
    import jax

    fn = getattr(jax.lax, "pvary", None)
    return fn(x, axes) if fn is not None else x


def ring_attention(q, k, v, axis_name: str, axis_size: int, causal: bool = False,
                   scale: Optional[float] = None, pvary_axes=None):
    """Exact attention across a ring. Call inside shard_map.

    Args:
      q, k, v: [B, T_local, H, D] local shards (sequence axis sharded).
      axis_name: mesh axis carrying the sequence shards.
      axis_size: static number of devices on that axis (mesh.shape[axis]).
      causal: apply causal masking in GLOBAL sequence positions.
    Returns:
      [B, T_local, H, D] attention output for the local Q block.
    """
    import jax
    import jax.numpy as jnp

    B, T, H, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    idx = jax.lax.axis_index(axis_name)
    q_pos = idx * T + jnp.arange(T)  # [T] global positions of our queries

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    # Mark the accumulators as varying over every manual mesh axis so the
    # scan carry type is stable under shard_map's varying-axes checks.
    axes = tuple(pvary_axes) if pvary_axes else (axis_name,)
    o0 = _pvary(jnp.zeros((B, H, T, D), dtype=jnp.float32), axes)
    m0 = _pvary(jnp.full((B, H, T), _BIG_NEG, dtype=jnp.float32), axes)
    l0 = _pvary(jnp.zeros((B, H, T), dtype=jnp.float32), axes)

    def step(carry, s):
        o, m, l, k_cur, v_cur = carry
        src = (idx - s) % axis_size  # which shard's K/V we hold this step
        k_pos = src * T + jnp.arange(T)
        # scores: [B, H, T, S]
        scores = jnp.einsum(
            "bthd,bshd->bhts", q.astype(jnp.float32), k_cur.astype(jnp.float32)
        ) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]  # [T, S]
            scores = jnp.where(mask[None, None], scores, _BIG_NEG)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])
        if causal:
            p = jnp.where(mask[None, None], p, 0.0)
        l = l * alpha + p.sum(axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bhts,bshd->bhtd", p, v_cur.astype(jnp.float32)
        )
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o, m_new, l, k_next, v_next), None

    (o, m, l, _, _), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(axis_size)
    )
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B, T, H, D]


def ring_attention_sharded(q, k, v, mesh, causal: bool = False,
                           seq_axis: str = "sequence",
                           batch_axes=("data", "fsdp"),
                           head_axis: str = "tensor"):
    """Global-view wrapper: q/k/v are [B, T, H, D] jax.Arrays; sequence is
    sharded over `seq_axis`, heads optionally over `head_axis`."""
    from jax.sharding import PartitionSpec as P

    present = set(mesh.axis_names)
    b_ax = tuple(a for a in batch_axes if a in present) or None
    h_ax = head_axis if head_axis in present else None
    s_ax = seq_axis if seq_axis in present else None
    if s_ax is None:
        return full_attention(q, k, v, causal=causal)
    spec = P(b_ax, s_ax, h_ax, None)
    axis_size = mesh.shape[s_ax]
    manual_axes = []
    for part in (b_ax, s_ax, h_ax):
        if part is None:
            continue
        if isinstance(part, tuple):
            manual_axes.extend(part)
        else:
            manual_axes.append(part)

    fn = functools.partial(
        ring_attention,
        axis_name=s_ax,
        axis_size=axis_size,
        causal=causal,
        pvary_axes=tuple(manual_axes),
    )
    sm = _shard_map()
    try:
        mapped = sm(
            fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_rep=False,
        )
    except TypeError:  # newer jax: check_rep retired with the pvary typing
        mapped = sm(fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return mapped(q, k, v)


def ring_attention_on_group(q, k, v, causal: bool = False,
                            group_name: str = "default"):
    """Ring attention over an xla collective group's mesh: the shard_map
    program is compiled once per (shape, dtype, causal) and cached on the
    group's MeshCollectives engine, so repeated calls skip retracing
    entirely. q/k/v: [B, T, H, D] with T sharded over the group axis."""
    from ray_tpu.util.collective import get_group_collectives

    eng = get_group_collectives(group_name)
    if eng is None:
        raise ValueError(
            f"group {group_name!r} has no mesh engine (xla backend required)"
        )
    return eng.ring_attention(q, k, v, causal=causal)


def full_attention(q, k, v, causal: bool = False, scale: Optional[float] = None):
    """Plain (unsharded) softmax attention; reference for tests and the
    no-sequence-axis fallback. Shapes [B, T, H, D]."""
    import jax.numpy as jnp

    B, T, H, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    scores = jnp.einsum(
        "bthd,bshd->bhts", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        S = k.shape[1]
        mask = jnp.arange(T)[:, None] >= jnp.arange(S)[None, :]
        scores = jnp.where(mask[None, None], scores, _BIG_NEG)
    import jax

    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
