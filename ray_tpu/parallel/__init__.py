"""ray_tpu.parallel: meshes, sharding rules, and context parallelism.

DP/FSDP/TP/SP are axes of one jax.sharding.Mesh; XLA lowers the collectives
onto ICI. See mesh.py for axis conventions, ring_attention.py / ulysses.py
for the long-context primitives.
"""

from ray_tpu.parallel.mesh import (
    CANONICAL_ORDER,
    DATA,
    EXPERT,
    FSDP,
    SEQUENCE,
    TENSOR,
    MeshSpec,
    ShardingRules,
    batch_sharding,
    data_parallel_spec,
    default_transformer_rules,
    fsdp_sharding_for_leaf,
    make_mesh,
    shard_pytree,
)
from ray_tpu.parallel.ring_attention import (
    full_attention,
    ring_attention,
    ring_attention_on_group,
    ring_attention_sharded,
)
from ray_tpu.parallel.ulysses import (
    ulysses_attention,
    ulysses_attention_on_group,
    ulysses_attention_sharded,
)

__all__ = [
    "DATA",
    "FSDP",
    "TENSOR",
    "SEQUENCE",
    "EXPERT",
    "CANONICAL_ORDER",
    "MeshSpec",
    "ShardingRules",
    "make_mesh",
    "batch_sharding",
    "data_parallel_spec",
    "default_transformer_rules",
    "fsdp_sharding_for_leaf",
    "shard_pytree",
    "ring_attention",
    "ring_attention_on_group",
    "ring_attention_sharded",
    "full_attention",
    "ulysses_attention",
    "ulysses_attention_on_group",
    "ulysses_attention_sharded",
]
