"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

Alternative context-parallel scheme to ring attention: instead of rotating
K/V blocks, one `all_to_all` regathers the full sequence while splitting
heads across the axis, each device runs plain attention on its head subset,
and a second all_to_all restores sequence sharding. Better when
heads >= axis_size and ICI all-to-all bandwidth is plentiful; ring wins on
very long sequences (constant memory) — ship both, pick per workload.
"""

from __future__ import annotations

from typing import Callable, Optional

from ray_tpu.parallel.ring_attention import _shard_map, full_attention


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False,
                      attn_fn: Optional[Callable] = None):
    """Call inside shard_map. q/k/v: [B, T_local, H, D], sequence sharded
    over axis_name; H must be divisible by the axis size."""
    import jax

    if attn_fn is None:
        attn_fn = full_attention
    # [B, T/N, H, D] -> [B, T, H/N, D]
    q2 = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    k2 = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    v2 = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    out = attn_fn(q2, k2, v2, causal=causal)
    # [B, T, H/N, D] -> [B, T/N, H, D]
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention_on_group(q, k, v, causal: bool = False,
                               group_name: str = "default"):
    """Ulysses attention over an xla collective group's mesh with the
    group's compiled-program cache (see ring_attention_on_group)."""
    from ray_tpu.util.collective import get_group_collectives

    eng = get_group_collectives(group_name)
    if eng is None:
        raise ValueError(
            f"group {group_name!r} has no mesh engine (xla backend required)"
        )
    return eng.ulysses_attention(q, k, v, causal=causal)


def ulysses_attention_sharded(q, k, v, mesh, causal: bool = False,
                              seq_axis: str = "sequence",
                              batch_axes=("data", "fsdp")):
    import functools

    from jax.sharding import PartitionSpec as P

    present = set(mesh.axis_names)
    if seq_axis not in present:
        return full_attention(q, k, v, causal=causal)
    b_ax = tuple(a for a in batch_axes if a in present) or None
    spec = P(b_ax, seq_axis, None, None)
    fn = functools.partial(ulysses_attention, axis_name=seq_axis, causal=causal)
    sm = _shard_map()
    try:
        mapped = sm(
            fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_rep=False,
        )
    except TypeError:  # newer jax: check_rep retired
        mapped = sm(fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return mapped(q, k, v)
