"""`ray-tpu` CLI: cluster lifecycle, jobs, state, dashboard.

Analog of python/ray/scripts/scripts.py (ray start/stop/status/submit at
:568,1044,1990,1355) + the job CLI (dashboard/modules/job/cli.py) + state
CLI (util/state/state_cli.py). argparse-based; also runnable as
`python -m ray_tpu.scripts.cli`.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
from typing import Optional

from ray_tpu._private.worker import cluster_state_file

STATE_FILE = cluster_state_file()


def _write_state(address: str, dashboard: Optional[str] = None) -> None:
    with open(STATE_FILE, "w") as f:
        json.dump(
            {"address": address, "pid": os.getpid(), "dashboard": dashboard}, f
        )


def _read_state() -> Optional[dict]:
    try:
        with open(STATE_FILE) as f:
            return json.load(f)
    except Exception:
        return None


def _resolve_address(args) -> str:
    if getattr(args, "address", None):
        return args.address
    env = os.environ.get("RAY_TPU_ADDRESS")
    if env:
        return env
    state = _read_state()
    if state:
        return state["address"]
    print("error: no running cluster found (pass --address)", file=sys.stderr)
    sys.exit(1)


# -- ray-tpu start / stop ------------------------------------------------------


def cmd_start(args) -> None:
    import asyncio

    from ray_tpu._private.node import Node

    if not args.head:
        print("error: worker-node mode needs --address; use ray-tpu start --head "
              "or connect raylets via `python -m ray_tpu._private.raylet`",
              file=sys.stderr)
        sys.exit(1)

    async def main():
        node = Node(
            head=True,
            num_cpus=args.num_cpus,
            num_tpus=args.num_tpus,
            object_store_memory=args.object_store_memory,
        )
        await node.start()
        address = f"{node.gcs_addr[0]}:{node.gcs_addr[1]}"
        dash_addr = None
        dash = None
        if not args.no_dashboard:
            from ray_tpu.dashboard.dashboard import Dashboard

            dash = Dashboard(
                node.gcs_addr,
                port=args.dashboard_port,
                session_name=node.session_name,
            )
            host, port = await dash.start()
            dash_addr = f"http://{host}:{port}"
        client_srv = None
        if args.client_server_port >= 0:
            from ray_tpu.util.client.server import ClientServer

            client_srv = ClientServer(
                node.gcs_addr,
                host=args.client_server_host,
                port=args.client_server_port,
            )
            chost, cport = await client_srv.start()
        _write_state(address, dash_addr)
        print(f"ray_tpu head started at {address}")
        if dash_addr:
            print(f"dashboard: {dash_addr}")
        print(f"connect with ray_tpu.init(address='{address}') or address='auto'")
        if client_srv is not None:
            print(
                "remote drivers: "
                f"ray_tpu.init(address='ray-tpu://{chost}:{cport}')"
            )
            if chost in ("127.0.0.1", "localhost"):
                print(
                    "  (bound to loopback; pass --client-server-host 0.0.0.0 "
                    "and firewall the port to accept off-host drivers)"
                )
        stop_event = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop_event.set)
        await stop_event.wait()
        if client_srv is not None:
            await client_srv.stop()
        if dash is not None:
            await dash.stop()
        await node.stop()

    from ray_tpu._private import rpc

    rpc.install_event_loop()
    asyncio.run(main())


def cmd_stop(args) -> None:
    state = _read_state()
    if state is None:
        print("no running cluster")
        return
    try:
        os.kill(state["pid"], signal.SIGTERM)
        print(f"sent SIGTERM to head process {state['pid']}")
    except ProcessLookupError:
        print("head process already gone")
    try:
        os.unlink(STATE_FILE)
    except OSError:
        pass


# -- ray-tpu status ------------------------------------------------------------


def cmd_status(args) -> None:
    import ray_tpu

    ray_tpu.init(address=_resolve_address(args))
    nodes = ray_tpu.nodes()
    total = ray_tpu.cluster_resources()
    avail = ray_tpu.available_resources()
    print(f"nodes: {sum(1 for n in nodes if n['state'] == 'ALIVE')} alive / {len(nodes)}")
    for k in sorted(total):
        print(f"  {k}: {avail.get(k, 0):g} / {total[k]:g} available")
    from ray_tpu.util.state import summarize_actors

    s = summarize_actors()
    print(f"actors: {s['total_actors']}")
    ray_tpu.shutdown()


# -- ray-tpu job ... -----------------------------------------------------------


def cmd_job(args) -> None:
    from ray_tpu.job import JobSubmissionClient

    client = JobSubmissionClient(address=_resolve_address(args))
    if args.job_cmd == "submit":
        entrypoint = " ".join(args.entrypoint)
        sid = client.submit_job(entrypoint=entrypoint)
        print(f"submitted job {sid}")
        if args.wait:
            status = client.wait_until_finish(sid, timeout_s=args.timeout)
            print(client.get_job_logs(sid), end="")
            print(f"job {sid}: {status}")
            sys.exit(0 if status == "SUCCEEDED" else 1)
    elif args.job_cmd == "status":
        print(client.get_job_status(args.id))
    elif args.job_cmd == "logs":
        print(client.get_job_logs(args.id), end="")
    elif args.job_cmd == "list":
        for info in client.list_jobs():
            print(f"{info.submission_id}  {info.status:10s}  {info.entrypoint}")
    elif args.job_cmd == "stop":
        ok = client.stop_job(args.id)
        print("stopped" if ok else "not found")


# -- ray-tpu summary / timeline ------------------------------------------------


def cmd_up(args) -> None:
    """Boot a cluster from a YAML (reference: `ray up`, scripts.py:1279)."""
    import time as _time

    from ray_tpu.autoscaler.launcher import ClusterConfig, ClusterLauncher

    launcher = ClusterLauncher(ClusterConfig.from_yaml(args.config))
    addr = launcher.up()
    print(f"cluster up; head address: {addr}")
    if args.monitor:
        print("autoscaler monitor running (ctrl-c to detach)...")
        try:
            while True:
                launcher.update()
                _time.sleep(launcher.autoscaler.config.poll_interval_s)
        except KeyboardInterrupt:
            pass


def cmd_down(args) -> None:
    """Tear down a cluster (reference: `ray down`, scripts.py:1355)."""
    from ray_tpu.autoscaler.launcher import (
        ClusterConfig,
        ClusterLauncher,
        read_cluster_state,
    )

    config = ClusterConfig.from_yaml(args.config)
    state = read_cluster_state(config.cluster_name)
    launcher = ClusterLauncher(config)
    launcher._make_provider()
    if state:
        launcher.head_address = state.get("head_address")
        launcher._worker_pids = state.get("worker_pids", [])
    # A fresh process has no in-memory node table: adopt what the cloud
    # reports before terminating.
    discover = getattr(launcher.provider, "discover_nodes", None)
    if discover is not None:
        discover()
    launcher.down()
    print(f"cluster {config.cluster_name} down")


def cmd_submit(args) -> None:
    """Submit an entrypoint against a cluster booted with `up`."""
    from ray_tpu.autoscaler.launcher import ClusterConfig, read_cluster_state
    from ray_tpu.job import JobSubmissionClient

    config = ClusterConfig.from_yaml(args.config)
    state = read_cluster_state(config.cluster_name)
    if not state:
        raise SystemExit(f"no running cluster named {config.cluster_name!r}")
    # argparse REMAINDER may include the literal "--" separator as the
    # first token; anything after it (including dashes) IS the entrypoint.
    tokens = list(args.entrypoint)
    if tokens and tokens[0] == "--":
        tokens = tokens[1:]
    entry = " ".join(tokens)
    client = JobSubmissionClient(state["head_address"])
    sid = client.submit_job(entrypoint=entry)
    print(f"submitted job {sid}")
    if not args.no_wait:
        import time as _time

        while True:
            info = client.get_job_info(sid)
            if info.status in ("SUCCEEDED", "FAILED", "STOPPED"):
                print(f"job {sid}: {info.status}")
                break
            _time.sleep(0.5)


def cmd_summary(args) -> None:
    import ray_tpu
    from ray_tpu.util import state as state_api

    ray_tpu.init(address=_resolve_address(args))
    fn = {
        "tasks": state_api.summarize_tasks,
        "actors": state_api.summarize_actors,
        "objects": state_api.summarize_objects,
    }[args.kind]
    print(json.dumps(fn(), indent=2))
    ray_tpu.shutdown()


def cmd_list(args) -> None:
    import ray_tpu
    from ray_tpu.util import state as state_api

    ray_tpu.init(address=_resolve_address(args))
    fn = getattr(state_api, f"list_{args.kind}")
    print(json.dumps(fn(limit=args.limit), indent=2, default=str))
    ray_tpu.shutdown()


def cmd_timeline(args) -> None:
    import ray_tpu
    from ray_tpu.util.state import critical_path, timeline

    ray_tpu.init(address=_resolve_address(args))
    if args.critical_path:
        report = critical_path(trace_id=args.trace_id)
        if not report["path"]:
            print("no trace spans recorded (enable RAY_TPU_TASK_TRACE_SPANS=1 "
                  "or RAY_TPU_TRACE_SAMPLE_RATE)")
        else:
            print(f"trace {report['trace_id']}  total {report['total_s']*1e3:.2f} ms")
            for seg in report["path"]:
                print(
                    f"  {seg['name']:<32} {seg['kind']:<12} "
                    f"dur {seg['duration_s']*1e3:8.2f} ms  "
                    f"self {seg['self_s']*1e3:8.2f} ms"
                )
            print(f"dominant segment: {report['dominant']}")
    else:
        events = timeline(args.output)
        print(f"wrote {len(events)} events to {args.output}")
    ray_tpu.shutdown()


def cmd_dashboard(args) -> None:
    import asyncio

    from ray_tpu.dashboard.dashboard import run_dashboard

    host, port = _resolve_address(args).rsplit(":", 1)
    asyncio.run(run_dashboard((host, int(port)), port=args.port))


# -- parser --------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ray-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="start a head node (blocking)")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--num-cpus", type=float, default=None)
    sp.add_argument("--num-tpus", type=float, default=None)
    sp.add_argument("--object-store-memory", type=int, default=None)
    sp.add_argument("--no-dashboard", action="store_true")
    sp.add_argument("--dashboard-port", type=int, default=8265)
    # Remote-driver proxy (reference: Ray Client, default port 10001).
    # 0 = ephemeral port, negative = disabled.
    sp.add_argument("--client-server-port", type=int, default=10001)
    # The client protocol executes pickled code with no authentication, so
    # bind loopback by default; exposing it (0.0.0.0) is an explicit opt-in
    # and the port must then be firewalled (matches reference Ray Client
    # guidance).
    sp.add_argument("--client-server-host", default="127.0.0.1")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("stop", help="stop the head started on this machine")
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser("status", help="cluster resource summary")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser("job", help="job submission")
    sp.add_argument("--address", default=None)
    jsub = sp.add_subparsers(dest="job_cmd", required=True)
    j = jsub.add_parser("submit")
    j.add_argument("--wait", action="store_true")
    j.add_argument("--timeout", type=float, default=600.0)
    j.add_argument("entrypoint", nargs=argparse.REMAINDER)
    for name in ("status", "logs", "stop"):
        j = jsub.add_parser(name)
        j.add_argument("id")
    jsub.add_parser("list")
    sp.set_defaults(fn=cmd_job)

    sp = sub.add_parser("up", help="boot a cluster from a YAML config")
    sp.add_argument("config", help="cluster YAML (see autoscaler/launcher.py)")
    sp.add_argument(
        "--monitor", action="store_true",
        help="keep running the autoscaler loop after bring-up",
    )
    sp.set_defaults(fn=cmd_up)

    sp = sub.add_parser("down", help="tear down a cluster booted with `up`")
    sp.add_argument("config", help="cluster YAML used for `up`")
    sp.set_defaults(fn=cmd_down)

    sp = sub.add_parser("submit", help="submit an entrypoint to a cluster")
    sp.add_argument("config", help="cluster YAML used for `up`")
    sp.add_argument("--no-wait", action="store_true")
    sp.add_argument("entrypoint", nargs=argparse.REMAINDER)
    sp.set_defaults(fn=cmd_submit)

    sp = sub.add_parser("summary", help="summarize tasks/actors/objects")
    sp.add_argument("kind", choices=["tasks", "actors", "objects"])
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_summary)

    sp = sub.add_parser("list", help="list cluster entities")
    sp.add_argument(
        "kind",
        choices=[
            "nodes",
            "actors",
            "tasks",
            "workers",
            "objects",
            "jobs",
            "placement_groups",
        ],
    )
    sp.add_argument("--limit", type=int, default=100)
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("timeline", help="dump chrome://tracing timeline")
    sp.add_argument("--output", default="timeline.json")
    sp.add_argument("--address", default=None)
    sp.add_argument(
        "--critical-path",
        action="store_true",
        help="print the dominant span chain of a trace instead of dumping",
    )
    sp.add_argument(
        "--trace-id",
        default=None,
        help="trace to analyze with --critical-path (default: longest)",
    )
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser("dashboard", help="run the dashboard against a cluster")
    sp.add_argument("--address", default=None)
    sp.add_argument("--port", type=int, default=8265)
    sp.set_defaults(fn=cmd_dashboard)

    return p


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
