"""Actor API: ActorClass / ActorHandle / ActorMethod.

Analog of python/ray/actor.py: `@ray_tpu.remote` on a class yields an
ActorClass; `.remote(...)` asks the GCS to create the actor (GCS owns the
placement and restart FSM); the returned ActorHandle submits method calls
directly to the actor worker with per-handle sequence numbers. Handles
serialize as bare actor ids — any process re-attaches via its own core worker.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu._private import worker as worker_mod
from ray_tpu.remote_function import _build_resources, _strategy_fields


class ActorMethod:
    def __init__(
        self,
        handle: "ActorHandle",
        name: str,
        num_returns: int = 1,
        max_task_retries: Optional[int] = None,
        concurrency_group: Optional[str] = None,
    ):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._max_task_retries = max_task_retries
        self._concurrency_group = concurrency_group

    def options(
        self,
        *,
        num_returns: int = 1,
        max_task_retries: Optional[int] = None,
        concurrency_group: Optional[str] = None,
    ) -> "ActorMethod":
        return ActorMethod(
            self._handle, self._name, num_returns, max_task_retries,
            concurrency_group,
        )

    def remote(self, *args, **kwargs):
        retries = (
            self._max_task_retries
            if self._max_task_retries is not None
            else self._handle._max_task_retries
        )
        w = worker_mod.global_worker
        if w.mode == "client":
            refs = w.client.call_actor_method(
                self._handle._actor_id, self._name, args, kwargs,
                num_returns=self._num_returns, max_task_retries=retries,
                concurrency_group=self._concurrency_group,
            )
            return refs[0] if self._num_returns in (1, -1, "dynamic") else refs
        core = worker_mod._core()
        refs = core.try_submit_actor_task_fast(
            self._handle._actor_id,
            self._name,
            args,
            kwargs,
            num_returns=self._num_returns,
            max_task_retries=retries,
            concurrency_group=self._concurrency_group,
            loop=worker_mod.global_worker.loop,
        )
        if refs is None:  # large args need the async plasma path
            refs = worker_mod.global_worker.run_async(
                core.submit_actor_task(
                    self._handle._actor_id,
                    self._name,
                    args,
                    kwargs,
                    num_returns=self._num_returns,
                    max_task_retries=retries,
                    concurrency_group=self._concurrency_group,
                )
            )
        if self._num_returns in (1, -1, "dynamic"):
            # Dynamic generator calls resolve through ONE ref whose value is
            # the ObjectRefGenerator.
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        """Lazy DAG construction for compiled graphs (ray_tpu.dag)."""
        from ray_tpu.dag.nodes import ClassMethodNode

        return ClassMethodNode(self._handle, self._name, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor method {self._name!r} cannot be called directly; use .remote()"
        )


class ActorHandle:
    def __init__(self, actor_id: str, max_task_retries: int = 0):
        self._actor_id = actor_id
        # Default per-method retry budget (reference: @ray.remote
        # max_task_retries on the actor class; rides handle serialization).
        self._max_task_retries = max_task_retries

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __repr__(self):
        return f"ActorHandle({self._actor_id[:16]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._max_task_retries))

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id


class ActorClass:
    def __init__(self, cls, **options):
        self._cls = cls
        self._options = options
        self._pickled: Optional[bytes] = None
        functools.update_wrapper(self, cls, updated=[])

    def _get_pickled(self) -> bytes:
        if self._pickled is None:
            self._pickled = cloudpickle.dumps(self._cls)
        return self._pickled

    def options(self, **options) -> "ActorClass":
        merged = {**self._options, **options}
        clone = ActorClass(self._cls, **merged)
        clone._pickled = self._pickled
        return clone

    def remote(self, *args, **kwargs) -> ActorHandle:
        opts = self._options
        w = worker_mod.global_worker
        if w.mode == "client":
            return w.client.create_actor(self, args, kwargs)
        core = worker_mod._core()
        pg_id, bundle_index, strategy = _strategy_fields(opts)
        resources = _build_resources(opts)
        actor_id = worker_mod.global_worker.run_async(
            core.create_actor(
                self._get_pickled(),
                opts.get("name_override") or self._cls.__name__,
                args,
                kwargs,
                resources=resources,
                max_restarts=opts.get("max_restarts", 0),
                max_concurrency=opts.get("max_concurrency", 1),
                max_task_retries=opts.get("max_task_retries", 0),
                concurrency_groups=opts.get("concurrency_groups"),
                name=opts.get("name"),
                namespace=opts.get("namespace") or worker_mod.global_worker.namespace,
                lifetime=opts.get("lifetime"),
                get_if_exists=opts.get("get_if_exists", False),
                pg_id=pg_id,
                bundle_index=bundle_index,
                scheduling_strategy=strategy,
                runtime_env=opts.get("runtime_env"),
            ),
            timeout=300,
        )
        return ActorHandle(actor_id, opts.get("max_task_retries", 0))

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self._cls.__name__!r} cannot be instantiated directly; "
            "use .remote()"
        )
