"""User-facing Serve config dataclasses.

Analog of python/ray/serve/schema.py + config.py (DeploymentConfig,
AutoscalingConfig, HTTPOptions) — plain dataclasses, no pydantic dependency.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Queue-length autoscaling (reference: serve/config.py AutoscalingConfig;
    policy in serve/_private/autoscaling_state.py).

    Desired replicas = total ongoing requests / target_ongoing_requests,
    clamped to [min_replicas, max_replicas], smoothed by upscale/downscale
    delays.
    """

    min_replicas: int = 1
    max_replicas: int = 1
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 60.0
    metrics_interval_s: float = 0.5
    look_back_period_s: float = 5.0
    # Queue-driven scaling: routers report per-deployment queue depth
    # (requests waiting for a replica slot); the controller smooths the
    # total with this EWMA factor and adds it to ongoing load when sizing
    # the replica set, so sustained queueing scales up even while every
    # replica is saturated at max_ongoing_requests.
    queue_ewma_alpha: float = 0.5
    # Router metrics older than this are dropped from the depth sum
    # (a dead router's last report must not pin the deployment scaled up).
    queue_metric_staleness_s: float = 3.0

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "AutoscalingConfig":
        return cls(**d)


@dataclass
class DeploymentConfig:
    """Per-deployment config (reference: serve/config.py DeploymentConfig)."""

    num_replicas: int = 1
    max_ongoing_requests: int = 16
    # Router-side queue cap: requests waiting for a replica slot beyond this
    # are shed immediately with DeploymentOverloadedError (-1 -> the
    # config.serve_max_queued_requests default).
    max_queued_requests: int = -1
    # Continuous batching (reference: @serve.batch / Orca-style iteration
    # scheduling): >1 makes the replica coalesce concurrent requests to the
    # same method into one user-code call with a list argument. A batch
    # launches when full or batch_wait_timeout_s after its first request,
    # and the next batch forms while in-flight ones execute.
    max_batch_size: int = 1
    batch_wait_timeout_s: float = 0.01
    user_config: Optional[Any] = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 2.0
    health_check_timeout_s: float = 30.0
    graceful_shutdown_timeout_s: float = 10.0
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d = asdict(self)
        if self.autoscaling_config is not None:
            d["autoscaling_config"] = self.autoscaling_config.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DeploymentConfig":
        d = dict(d)
        if d.get("autoscaling_config"):
            d["autoscaling_config"] = AutoscalingConfig.from_dict(
                d["autoscaling_config"]
            )
        return cls(**d)


@dataclass
class HTTPOptions:
    """Proxy config (reference: serve/config.py HTTPOptions + gRPCOptions).

    ``grpc_port`` enables the gRPC ingress alongside HTTP: a generic
    bytes-in/bytes-out service routed by metadata (0 = ephemeral port,
    None = disabled)."""

    host: str = "127.0.0.1"
    port: int = 8000
    grpc_port: Optional[int] = None
    # False skips the proxy actor entirely (handle-only serving — loadgen
    # and the chaos serve suite drive the router directly).
    enabled: bool = True

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)
