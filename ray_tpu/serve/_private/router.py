"""Request router: pow-2-choices replica scheduling with local in-flight counts.

Analog of python/ray/serve/_private/router.py (Router:312) +
replica_scheduler/pow_2_scheduler.py: the router keeps a live replica set per
deployment (pushed from the controller via long-poll) and assigns each request
to the less-loaded of two randomly sampled replicas, respecting
max_ongoing_requests with backpressure.
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.actor import ActorHandle
from ray_tpu.serve._private.common import RunningReplicaInfo
from ray_tpu.serve._private.long_poll import LongPollClient

logger = logging.getLogger(__name__)


class _ReplicaSet:
    def __init__(self):
        self.replicas: List[RunningReplicaInfo] = []
        self.handles: Dict[str, ActorHandle] = {}
        self.ongoing: Dict[str, int] = {}
        self.nonempty = asyncio.Event()
        self.slot_freed = asyncio.Event()
        # model_id -> replica_id_str sticky routing for @serve.multiplexed.
        self.model_affinity: Dict[str, str] = {}

    def update(self, infos: List[RunningReplicaInfo]) -> None:
        self.replicas = infos
        new_ids = {r.replica_id_str for r in infos}
        for info in infos:
            if info.replica_id_str not in self.handles:
                self.handles[info.replica_id_str] = ActorHandle(info.actor_id)
                self.ongoing.setdefault(info.replica_id_str, 0)
        for rid in list(self.handles):
            if rid not in new_ids:
                del self.handles[rid]
                self.ongoing.pop(rid, None)
        for mid, rid in list(self.model_affinity.items()):
            if rid not in new_ids:
                del self.model_affinity[mid]
        if infos:
            self.nonempty.set()
        else:
            self.nonempty.clear()


class Router:
    """One per handle-owning process per deployment-consumer (driver, replica,
    or proxy)."""

    def __init__(self, controller_handle: ActorHandle, core):
        self._controller = controller_handle
        self._core = core
        self._sets: Dict[str, _ReplicaSet] = {}
        self._poll_client: Optional[LongPollClient] = None
        self._watched: Dict[str, bool] = {}

    def _replica_set(self, deployment_id_str: str) -> _ReplicaSet:
        rs = self._sets.get(deployment_id_str)
        if rs is None:
            rs = _ReplicaSet()
            self._sets[deployment_id_str] = rs
        return rs

    async def _listen(self, keys_to_ids: Dict[str, int]):
        refs = await self._core.submit_actor_task(
            self._controller._actor_id,
            "listen_for_change",
            (keys_to_ids,),
            {},
            num_returns=1,
        )
        return await self._core.get_objects(refs[0], timeout=None)

    def watch(self, deployment_id_str: str) -> None:
        """Subscribe to replica-set updates for a deployment (idempotent).
        Restarts the long-poll client with the union of watched keys."""
        if self._watched.get(deployment_id_str):
            return
        self._watched[deployment_id_str] = True
        if self._poll_client is not None:
            self._poll_client.stop()
        listeners = {}
        for dep in self._watched:
            key = f"replicas::{dep}"

            def make_cb(dep_id=dep):
                def cb(value):
                    infos = [RunningReplicaInfo.from_dict(d) for d in (value or [])]
                    self._replica_set(dep_id).update(infos)

                return cb

            listeners[key] = make_cb()
        self._poll_client = LongPollClient(self._listen, listeners)
        self._poll_client.start()

    def shutdown(self) -> None:
        if self._poll_client is not None:
            self._poll_client.stop()

    # -- scheduling ----------------------------------------------------------

    def _pick_replica(
        self, rs: _ReplicaSet, model_id: Optional[str] = None
    ) -> Optional[RunningReplicaInfo]:
        candidates = [
            r
            for r in rs.replicas
            if rs.ongoing.get(r.replica_id_str, 0) < r.max_ongoing_requests
        ]
        if not candidates:
            return None
        if model_id:
            # Multiplexed-model affinity (reference: multiplexed routing):
            # keep one model's requests on the replica that already loaded
            # it, so per-replica model caches actually hit.
            preferred = rs.model_affinity.get(model_id)
            if preferred is not None:
                for r in candidates:
                    if r.replica_id_str == preferred:
                        return r
                if any(r.replica_id_str == preferred for r in rs.replicas):
                    # Pinned replica is alive but momentarily full: wait for
                    # a slot instead of rebinding (a rebind cold-loads the
                    # model elsewhere and thrashes both replicas' caches).
                    return None
        sampled = random.sample(candidates, min(2, len(candidates)))
        pick = min(sampled, key=lambda r: rs.ongoing.get(r.replica_id_str, 0))
        if model_id:
            rs.model_affinity[model_id] = pick.replica_id_str
            while len(rs.model_affinity) > 256:
                rs.model_affinity.pop(next(iter(rs.model_affinity)))
        return pick

    async def _acquire_replica(
        self,
        deployment_id_str: str,
        request_meta: Dict[str, Any],
        timeout_s: Optional[float],
    ):
        """Pick a replica (pow-2 with backpressure waits); returns
        (replica_set, replica) with NO ongoing-count taken yet."""
        self.watch(deployment_id_str)
        rs = self._replica_set(deployment_id_str)
        loop = asyncio.get_running_loop()
        deadline = None if timeout_s is None else loop.time() + timeout_s
        while True:
            if not rs.replicas:
                wait = None if deadline is None else max(0, deadline - loop.time())
                try:
                    await asyncio.wait_for(rs.nonempty.wait(), timeout=wait)
                except asyncio.TimeoutError:
                    raise TimeoutError(
                        f"no replicas of {deployment_id_str} available"
                    ) from None
            replica = self._pick_replica(
                rs, request_meta.get("multiplexed_model_id")
            )
            if replica is not None:
                break
            # All replicas at max_ongoing_requests: wait for a slot.
            rs.slot_freed.clear()
            try:
                await asyncio.wait_for(
                    rs.slot_freed.wait(),
                    timeout=0.5
                    if deadline is None
                    else min(0.5, max(0.01, deadline - loop.time())),
                )
            except asyncio.TimeoutError:
                if deadline is not None and loop.time() > deadline:
                    raise TimeoutError(
                        f"backpressure timeout for {deployment_id_str}"
                    ) from None
        return rs, replica

    async def assign_request(
        self,
        deployment_id_str: str,
        request_meta: Dict[str, Any],
        args: Tuple,
        kwargs: Dict,
        timeout_s: Optional[float] = None,
    ) -> Any:
        """Route one request and return its result value."""
        rs, replica = await self._acquire_replica(
            deployment_id_str, request_meta, timeout_s
        )
        rid = replica.replica_id_str
        rs.ongoing[rid] = rs.ongoing.get(rid, 0) + 1
        try:
            refs = await self._core.submit_actor_task(
                self._handle_for(rs, replica)._actor_id,
                "handle_request",
                (request_meta, args, kwargs),
                {},
                num_returns=1,
            )
            return await self._core.get_objects(refs[0], timeout=None)
        finally:
            rs.ongoing[rid] = max(0, rs.ongoing.get(rid, 1) - 1)
            rs.slot_freed.set()

    async def assign_request_streaming(
        self,
        deployment_id_str: str,
        request_meta: Dict[str, Any],
        args: Tuple,
        kwargs: Dict,
        timeout_s: Optional[float] = None,
    ):
        """Route one request to the streaming handler; async-yields each
        item as the replica produces it (the runtime's streaming-generator
        machinery carries items owner-ward while the replica still runs —
        reference: router.py + replica.py handle_request_streaming)."""
        rs, replica = await self._acquire_replica(
            deployment_id_str, request_meta, timeout_s
        )
        rid = replica.replica_id_str
        rs.ongoing[rid] = rs.ongoing.get(rid, 0) + 1
        try:
            refs = await self._core.submit_actor_task(
                self._handle_for(rs, replica)._actor_id,
                "handle_request_streaming",
                (request_meta, args, kwargs),
                {},
                num_returns=-1,
            )
            gen = await self._core.get_objects(refs[0], timeout=None)
            i = 0
            while True:
                if gen._refs is not None:  # fully-materialized legacy form
                    if i >= len(gen._refs):
                        break
                    ref = gen._refs[i]
                else:
                    ref = await self._core.dyn_next(
                        gen._task_id, gen._owner_addr, i
                    )
                    if ref is None:
                        break
                yield await self._core.get_objects(ref, timeout=None)
                i += 1
        finally:
            rs.ongoing[rid] = max(0, rs.ongoing.get(rid, 1) - 1)
            rs.slot_freed.set()

    def _handle_for(self, rs: _ReplicaSet, info: RunningReplicaInfo) -> ActorHandle:
        h = rs.handles.get(info.replica_id_str)
        if h is None:
            h = ActorHandle(info.actor_id)
            rs.handles[info.replica_id_str] = h
        return h
