"""Request router: pow-2-choices replica scheduling with local in-flight counts.

Analog of python/ray/serve/_private/router.py (Router:312) +
replica_scheduler/pow_2_scheduler.py: the router keeps a live replica set per
deployment (pushed from the controller via long-poll) and assigns each request
to the less-loaded of two randomly sampled replicas, respecting
max_ongoing_requests with backpressure.

Overload story (docs/serving.md): every request carries a deadline (explicit
``timeout_s`` folded with the ambient RPC deadline), and the router is the
admission gate —

- a request whose remaining budget cannot cover the deployment's observed
  service-time estimate (EWMA over completed requests, times a safety
  factor) is shed at the door with a typed DeploymentOverloadedError
  instead of burning a replica slot only to be cut at the wire deadline;
- requests waiting for a replica slot count against a per-deployment queue
  cap (max_queued_requests); overflow sheds immediately, bounding memory
  under open-loop storms;
- admitted requests ride the PR-4 TTL stamps to the replica (the ambient
  deadline is set around the actor call), so the replica-side server sheds
  or cancels them at the deadline and the error reply comes back typed.

The router also pushes per-deployment queue depth + ongoing counts to the
controller at a fixed cadence; that feed drives the queue-EWMA autoscaler.
"""

from __future__ import annotations

import asyncio
import logging
import random
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import rpc, telemetry
from ray_tpu._private.common import (
    ActorDiedError,
    ActorUnavailableError,
    config,
)
from ray_tpu.actor import ActorHandle
from ray_tpu.serve._private.common import (
    DeploymentOverloadedError,
    RunningReplicaInfo,
)
from ray_tpu.serve._private.long_poll import LongPollClient
from ray_tpu.util import tracing

logger = logging.getLogger(__name__)

_TEL_SHED_QUEUE = telemetry.counter(
    "serve", "shed_queue_full", "requests shed at the door: queue cap reached"
)
_TEL_SHED_DEADLINE = telemetry.counter(
    "serve", "shed_deadline",
    "requests shed at admission: budget below service estimate",
)
_TEL_COMPLETED = telemetry.counter(
    "serve", "requests_completed", "requests completed through the router"
)
_TEL_EVICTED = telemetry.counter(
    "serve", "replicas_evicted", "replicas locally evicted as observed-dead"
)
_TEL_SERVICE_TIME = telemetry.histogram(
    "serve", "service_time_s",
    "end-to-end request service time observed by the router",
    buckets=telemetry.LATENCY_BUCKETS_S,
)


class _ReplicaSet:
    def __init__(self, dep: str = "?"):
        self.replicas: List[RunningReplicaInfo] = []
        self.handles: Dict[str, ActorHandle] = {}
        self.ongoing: Dict[str, int] = {}
        self.nonempty = asyncio.Event()
        self.slot_freed = asyncio.Event()
        # model_id -> replica_id_str sticky routing for @serve.multiplexed.
        self.model_affinity: Dict[str, str] = {}
        # Admission-control state: requests currently waiting for a replica
        # slot, and the EWMA of observed request service time (queue wait at
        # the replica included — that is the latency a new request will see).
        self.queued = 0
        self.ewma_service_s: Optional[float] = None
        # Shed/outcome counters (surfaced via Router.stats() for loadgen,
        # tests, and the chaos serve invariant).
        self.shed_queue_full = 0
        self.shed_deadline = 0
        self.deadline_failures = 0
        self.completed = 0
        self.evicted = 0
        self.dep = dep
        # Telemetry twins of the counters above, labeled by deployment
        # (the plain ints stay: loadgen/chaos read them via stats()).
        self._tel_shed_queue = _TEL_SHED_QUEUE.cell(deployment=dep)
        self._tel_shed_deadline = _TEL_SHED_DEADLINE.cell(deployment=dep)
        self._tel_completed = _TEL_COMPLETED.cell(deployment=dep)
        self._tel_evicted = _TEL_EVICTED.cell(deployment=dep)
        self._tel_service_time = _TEL_SERVICE_TIME.cell(deployment=dep)

    def update(self, infos: List[RunningReplicaInfo]) -> None:
        self.replicas = infos
        new_ids = {r.replica_id_str for r in infos}
        for info in infos:
            if info.replica_id_str not in self.handles:
                self.handles[info.replica_id_str] = ActorHandle(info.actor_id)
                self.ongoing.setdefault(info.replica_id_str, 0)
        for rid in list(self.handles):
            if rid not in new_ids:
                del self.handles[rid]
                self.ongoing.pop(rid, None)
        for mid, rid in list(self.model_affinity.items()):
            if rid not in new_ids:
                del self.model_affinity[mid]
        if infos:
            self.nonempty.set()
        else:
            self.nonempty.clear()

    def evict(self, replica_id_str: str) -> None:
        """Drop a replica the data plane just observed dead. The controller's
        health checks lag the death by up to 3 check periods, and until it
        notices, every long-poll push re-lists the corpse — evicting locally
        closes that window so queued requests re-route instead of piling
        typed failures onto a replica that cannot answer."""
        before = len(self.replicas)
        self.replicas = [
            r for r in self.replicas if r.replica_id_str != replica_id_str
        ]
        if len(self.replicas) == before:
            return
        self.evicted += 1
        self._tel_evicted.inc()
        telemetry.record_event(
            "serve", "replica_evict", deployment=self.dep,
            replica=replica_id_str,
        )
        self.handles.pop(replica_id_str, None)
        self.ongoing.pop(replica_id_str, None)
        for mid, rid in list(self.model_affinity.items()):
            if rid == replica_id_str:
                del self.model_affinity[mid]
        if not self.replicas:
            self.nonempty.clear()
        # Wake queued pickers: the dead replica's phantom slots are gone.
        self.slot_freed.set()

    def queue_cap(self) -> int:
        for info in self.replicas:
            if info.max_queued_requests >= 0:
                return info.max_queued_requests
        return config.serve_max_queued_requests

    def observe_service_time(self, seconds: float) -> None:
        self.completed += 1
        self._tel_completed.inc()
        self._tel_service_time.observe(seconds)
        if self.ewma_service_s is None:
            self.ewma_service_s = seconds
        else:
            alpha = config.serve_admission_ewma_alpha
            self.ewma_service_s = (
                alpha * seconds + (1.0 - alpha) * self.ewma_service_s
            )


class Router:
    """One per handle-owning process per deployment-consumer (driver, replica,
    or proxy)."""

    def __init__(self, controller_handle: ActorHandle, core):
        self._controller = controller_handle
        self._core = core
        self._sets: Dict[str, _ReplicaSet] = {}
        self._poll_client: Optional[LongPollClient] = None
        self._watched: Dict[str, bool] = {}
        self._router_id = uuid.uuid4().hex[:8]
        self._metrics_task: Optional[asyncio.Task] = None
        self._stopped = False

    def _replica_set(self, deployment_id_str: str) -> _ReplicaSet:
        rs = self._sets.get(deployment_id_str)
        if rs is None:
            rs = _ReplicaSet(deployment_id_str)
            self._sets[deployment_id_str] = rs
        return rs

    async def _listen(self, keys_to_ids: Dict[str, int]):
        refs = await self._core.submit_actor_task(
            self._controller._actor_id,
            "listen_for_change",
            (keys_to_ids,),
            {},
            num_returns=1,
        )
        return await self._core.get_objects(refs[0], timeout=None)

    def watch(self, deployment_id_str: str) -> None:
        """Subscribe to replica-set updates for a deployment (idempotent).
        Restarts the long-poll client with the union of watched keys."""
        if self._watched.get(deployment_id_str):
            return
        self._watched[deployment_id_str] = True
        if self._poll_client is not None:
            self._poll_client.stop()
        listeners = {}
        for dep in self._watched:
            key = f"replicas::{dep}"

            def make_cb(dep_id=dep):
                def cb(value):
                    infos = [RunningReplicaInfo.from_dict(d) for d in (value or [])]
                    self._replica_set(dep_id).update(infos)

                return cb

            listeners[key] = make_cb()
        self._poll_client = LongPollClient(self._listen, listeners)
        self._poll_client.start()
        if self._metrics_task is None or self._metrics_task.done():
            self._metrics_task = rpc.spawn(self._metrics_loop())

    def shutdown(self) -> None:
        self._stopped = True
        if self._poll_client is not None:
            self._poll_client.stop()
        if self._metrics_task is not None:
            self._metrics_task.cancel()
            self._metrics_task = None

    def stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-deployment router-side counters (loadgen + tests)."""
        return {
            dep: {
                "queued": rs.queued,
                "ongoing": sum(rs.ongoing.values()),
                "shed_queue_full": rs.shed_queue_full,
                "shed_deadline": rs.shed_deadline,
                "deadline_failures": rs.deadline_failures,
                "completed": rs.completed,
                "evicted": rs.evicted,
                "ewma_service_s": rs.ewma_service_s,
            }
            for dep, rs in self._sets.items()
        }

    # -- autoscaler feed -----------------------------------------------------

    async def _metrics_loop(self) -> None:
        """Push queue depth + ongoing counts per deployment to the controller
        (the queue-EWMA autoscaler's input). Best effort: a dead controller
        just drops samples until it returns."""
        interval = config.serve_router_metrics_interval_s
        while not self._stopped:
            await asyncio.sleep(interval)
            snap = {
                dep: {"queued": rs.queued, "ongoing": sum(rs.ongoing.values())}
                for dep, rs in self._sets.items()
            }
            if not snap:
                continue
            try:
                refs = await self._core.submit_actor_task(
                    self._controller._actor_id,
                    "record_router_metrics",
                    (self._router_id, snap),
                    {},
                    num_returns=1,
                )
                await asyncio.wait_for(
                    self._core.get_objects(refs[0], timeout=None),
                    timeout=interval * 4,
                )
            except asyncio.CancelledError:
                return
            except Exception:
                pass

    # -- admission control ---------------------------------------------------

    @staticmethod
    def _request_deadline(loop, timeout_s: Optional[float]) -> Optional[float]:
        """Fold the caller's timeout with the ambient RPC deadline (a handle
        call made inside a deadlined handler never outlives its caller)."""
        local = None if timeout_s is None else loop.time() + timeout_s
        ambient = rpc.current_deadline()
        if ambient is None:
            return local
        if local is None:
            return ambient
        return min(local, ambient)

    def _admit_deadline(
        self, rs: _ReplicaSet, dep: str, deadline: Optional[float], loop
    ) -> None:
        """Shed if the remaining budget cannot cover the service estimate."""
        if deadline is None or rs.ewma_service_s is None:
            return
        remaining = deadline - loop.time()
        need = rs.ewma_service_s * config.serve_admission_safety_factor
        if remaining < need:
            rs.shed_deadline += 1
            rs._tel_shed_deadline.inc()
            telemetry.record_event(
                "serve", "admission_shed", deployment=dep,
                reason="deadline_unreachable",
            )
            raise DeploymentOverloadedError(
                dep,
                "deadline_unreachable",
                f"remaining budget {remaining * 1000:.0f}ms < "
                f"service estimate {need * 1000:.0f}ms",
            )

    # -- scheduling ----------------------------------------------------------

    def _pick_replica(
        self, rs: _ReplicaSet, model_id: Optional[str] = None
    ) -> Optional[RunningReplicaInfo]:
        candidates = [
            r
            for r in rs.replicas
            if rs.ongoing.get(r.replica_id_str, 0) < r.max_ongoing_requests
        ]
        if not candidates:
            return None
        if model_id:
            # Multiplexed-model affinity (reference: multiplexed routing):
            # keep one model's requests on the replica that already loaded
            # it, so per-replica model caches actually hit.
            preferred = rs.model_affinity.get(model_id)
            if preferred is not None:
                for r in candidates:
                    if r.replica_id_str == preferred:
                        return r
                if any(r.replica_id_str == preferred for r in rs.replicas):
                    # Pinned replica is alive but momentarily full: wait for
                    # a slot instead of rebinding (a rebind cold-loads the
                    # model elsewhere and thrashes both replicas' caches).
                    return None
        sampled = random.sample(candidates, min(2, len(candidates)))
        pick = min(sampled, key=lambda r: rs.ongoing.get(r.replica_id_str, 0))
        if model_id:
            rs.model_affinity[model_id] = pick.replica_id_str
            while len(rs.model_affinity) > 256:
                rs.model_affinity.pop(next(iter(rs.model_affinity)))
        return pick

    async def _acquire_replica(
        self,
        deployment_id_str: str,
        request_meta: Dict[str, Any],
        deadline: Optional[float],
    ):
        """Admission gate + pow-2 pick; returns (replica_set, replica) with
        NO ongoing-count taken yet. Raises DeploymentOverloadedError on a
        shed, TimeoutError when no replica ever materializes in budget."""
        self.watch(deployment_id_str)
        rs = self._replica_set(deployment_id_str)
        loop = asyncio.get_running_loop()
        self._admit_deadline(rs, deployment_id_str, deadline, loop)
        cap = rs.queue_cap()
        if rs.queued >= cap:
            rs.shed_queue_full += 1
            rs._tel_shed_queue.inc()
            telemetry.record_event(
                "serve", "admission_shed", deployment=deployment_id_str,
                reason="queue_full",
            )
            raise DeploymentOverloadedError(
                deployment_id_str,
                "queue_full",
                f"{rs.queued} queued >= cap {cap}",
            )
        poll = config.serve_backpressure_poll_s
        rs.queued += 1
        try:
            while True:
                if not rs.replicas:
                    wait = (
                        None
                        if deadline is None
                        else max(0.0, deadline - loop.time())
                    )
                    try:
                        await asyncio.wait_for(rs.nonempty.wait(), timeout=wait)
                    except asyncio.TimeoutError:
                        raise TimeoutError(
                            f"no replicas of {deployment_id_str} available"
                        ) from None
                replica = self._pick_replica(
                    rs, request_meta.get("multiplexed_model_id")
                )
                if replica is not None:
                    return rs, replica
                # All replicas at max_ongoing_requests: wait for a slot, then
                # re-run deadline admission — a request whose budget drained
                # away while queued becomes a typed shed, not a timeout.
                rs.slot_freed.clear()
                try:
                    await asyncio.wait_for(
                        rs.slot_freed.wait(),
                        timeout=poll
                        if deadline is None
                        else min(poll, max(0.01, deadline - loop.time())),
                    )
                except asyncio.TimeoutError:
                    if deadline is not None and loop.time() > deadline:
                        raise TimeoutError(
                            f"backpressure timeout for {deployment_id_str}"
                        ) from None
                self._admit_deadline(rs, deployment_id_str, deadline, loop)
        finally:
            rs.queued -= 1

    async def assign_request(
        self,
        deployment_id_str: str,
        request_meta: Dict[str, Any],
        args: Tuple,
        kwargs: Dict,
        timeout_s: Optional[float] = None,
    ) -> Any:
        """Route one request and return its result value."""
        loop = asyncio.get_running_loop()
        deadline = self._request_deadline(loop, timeout_s)
        # Root span for the whole routed request: a serve request has no
        # task ancestry, so the router is where its trace begins (sampled
        # on the request id). Every downstream hop — the actor submit, the
        # lease RPCs, the replica's execute scope — parents under this.
        with tracing.root_scope(
            f"serve.request::{deployment_id_str}",
            "serve",
            key=request_meta.get("request_id") or deployment_id_str,
            deployment=deployment_id_str,
        ):
            return await self._assign_request_traced(
                deployment_id_str, request_meta, args, kwargs, loop, deadline
            )

    async def _assign_request_traced(
        self,
        deployment_id_str: str,
        request_meta: Dict[str, Any],
        args: Tuple,
        kwargs: Dict,
        loop,
        deadline: Optional[float],
    ) -> Any:
        while True:
            with tracing.span_scope(
                "serve.admission", "serve", deployment=deployment_id_str
            ):
                rs, replica = await self._acquire_replica(
                    deployment_id_str, request_meta, deadline
                )
            rid = replica.replica_id_str
            rs.ongoing[rid] = rs.ongoing.get(rid, 0) + 1
            t0 = loop.time()
            # Admitted: the deadline rides the actor call as a TTL stamp, so
            # the replica-side server sheds it if it expires in transit and
            # cancels the handler at the deadline (PR-4 enforcement). The
            # grace window lets the typed error reply travel back before we
            # declare the request lost.
            token = (
                rpc._ambient_deadline.set(deadline)
                if deadline is not None
                else None
            )
            try:
                refs = await self._core.submit_actor_task(
                    self._handle_for(rs, replica)._actor_id,
                    "handle_request",
                    (request_meta, args, kwargs),
                    {},
                    num_returns=1,
                )
                get = self._core.get_objects(refs[0], timeout=None)
                if deadline is None:
                    result = await get
                else:
                    result = await asyncio.wait_for(
                        get,
                        timeout=max(0.0, deadline - loop.time())
                        + config.rpc_deadline_grace_s,
                    )
                rs.observe_service_time(loop.time() - t0)
                return result
            except asyncio.TimeoutError:
                rs.deadline_failures += 1
                raise rpc.DeadlineExceeded(
                    f"request to {deployment_id_str} missed its deadline "
                    f"(no reply within budget + grace)"
                ) from None
            except rpc.DeadlineExceeded:
                rs.deadline_failures += 1
                raise
            except ActorDiedError:
                # The replica was dead before the task ever ran (it only
                # raises at actor resolution). Evict it and re-route: the
                # retry re-enters admission, so a budget that drains away
                # while the deployment recovers becomes a typed shed or
                # deadline error, never a wasted slot on a corpse.
                rs.evict(rid)
                continue
            except ActorUnavailableError:
                # Died while the request was in flight — it may have
                # partially executed, so no blind re-execute: surface the
                # typed error, but stop routing new requests at the corpse.
                rs.evict(rid)
                raise
            except rpc.RpcError as e:
                if str(e).startswith("DeadlineExceeded"):
                    rs.deadline_failures += 1
                    raise rpc.DeadlineExceeded(str(e)) from None
                raise
            finally:
                if token is not None:
                    rpc._ambient_deadline.reset(token)
                if rid in rs.ongoing:
                    rs.ongoing[rid] = max(0, rs.ongoing[rid] - 1)
                rs.slot_freed.set()

    async def assign_request_streaming(
        self,
        deployment_id_str: str,
        request_meta: Dict[str, Any],
        args: Tuple,
        kwargs: Dict,
        timeout_s: Optional[float] = None,
    ):
        """Route one request to the streaming handler; async-yields each
        item as the replica produces it (the runtime's streaming-generator
        machinery carries items owner-ward while the replica still runs —
        reference: router.py + replica.py handle_request_streaming).

        Admission control applies at entry; the per-item waits are not
        deadline-cut (streams may legitimately outlive the initial budget)."""
        loop = asyncio.get_running_loop()
        deadline = self._request_deadline(loop, timeout_s)
        # Root span covering the stream (see assign_request): entered
        # manually because this is an async generator — the scope must stay
        # open across yields and close on exhaustion/teardown.
        scope = tracing.root_scope(
            f"serve.request::{deployment_id_str}",
            "serve",
            key=request_meta.get("request_id") or deployment_id_str,
            deployment=deployment_id_str,
            streaming=True,
        )
        scope.__enter__()
        try:
            async for item in self._assign_streaming_traced(
                deployment_id_str, request_meta, args, kwargs, deadline
            ):
                yield item
        finally:
            scope.__exit__(None, None, None)

    async def _assign_streaming_traced(
        self,
        deployment_id_str: str,
        request_meta: Dict[str, Any],
        args: Tuple,
        kwargs: Dict,
        deadline: Optional[float],
    ):
        while True:
            with tracing.span_scope(
                "serve.admission", "serve", deployment=deployment_id_str
            ):
                rs, replica = await self._acquire_replica(
                    deployment_id_str, request_meta, deadline
                )
            rid = replica.replica_id_str
            rs.ongoing[rid] = rs.ongoing.get(rid, 0) + 1
            yielded = False
            try:
                refs = await self._core.submit_actor_task(
                    self._handle_for(rs, replica)._actor_id,
                    "handle_request_streaming",
                    (request_meta, args, kwargs),
                    {},
                    num_returns=-1,
                )
                gen = await self._core.get_objects(refs[0], timeout=None)
                i = 0
                while True:
                    if gen._refs is not None:  # fully-materialized legacy form
                        if i >= len(gen._refs):
                            break
                        ref = gen._refs[i]
                    else:
                        ref = await self._core.dyn_next(
                            gen._task_id, gen._owner_addr, i
                        )
                        if ref is None:
                            break
                    item = await self._core.get_objects(ref, timeout=None)
                    yielded = True
                    yield item
                    i += 1
                return
            except ActorDiedError:
                # Dead at resolution: safe to re-route only while nothing
                # has been yielded — a consumed prefix cannot be replayed.
                rs.evict(rid)
                if yielded:
                    raise
                continue
            except ActorUnavailableError:
                rs.evict(rid)
                raise
            finally:
                if rid in rs.ongoing:
                    rs.ongoing[rid] = max(0, rs.ongoing[rid] - 1)
                rs.slot_freed.set()

    def _handle_for(self, rs: _ReplicaSet, info: RunningReplicaInfo) -> ActorHandle:
        h = rs.handles.get(info.replica_id_str)
        if h is None:
            h = ActorHandle(info.actor_id)
            rs.handles[info.replica_id_str] = h
        return h
