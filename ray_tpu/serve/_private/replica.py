"""Replica actor: hosts one copy of a user deployment.

Analog of python/ray/serve/_private/replica.py (ReplicaActor:231): wraps the
user callable, tracks ongoing-request count (consumed by the pow-2 router and
the autoscaler), exposes health checks and reconfigure.

Continuous dynamic batching (reference: @serve.batch; Orca-style iteration
scheduling, Yu et al. OSDI'22): when max_batch_size > 1, concurrent requests
to the same method are coalesced into one user-code call that receives a
LIST of inputs and must return a list of the same length. A batch launches
when it fills or batch_wait_timeout_s after its first request arrives — and
the NEXT batch keeps forming while in-flight batches execute, so admission
into batch N+1 overlaps batch N's compute (the "continuous" part).
"""

from __future__ import annotations

import asyncio
import contextvars
import inspect
import logging
import time
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu._private import telemetry
from ray_tpu._private.rpc import spawn as _spawn
from ray_tpu.util import tracing

logger = logging.getLogger(__name__)


class _BatchItem:
    __slots__ = ("value", "future", "enqueued_at", "enqueued_wall", "trace_ctx")

    def __init__(self, value, future, enqueued_at):
        self.value = value
        self.future = future
        self.enqueued_at = enqueued_at
        self.enqueued_wall = time.time()
        # Captured at submit: the pump/batch tasks run in the PUMP's
        # context, so the request's trace would be lost at the queue hop
        # without pinning it here (the batch counterpart of the
        # run_in_executor gap set_context documents).
        self.trace_ctx = tracing.current_context()


_TEL_BATCH_SIZE = telemetry.histogram(
    "serve", "batch_size",
    "dynamic-batch sizes launched by replica batch queues",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0),
).default


class _BatchStats:
    """Batch-size / queue-age counters, exposed via Replica.get_metrics."""

    __slots__ = (
        "batches",
        "requests",
        "size_max",
        "queue_age_sum_s",
        "queue_age_max_s",
    )

    def __init__(self):
        self.batches = 0
        self.requests = 0
        self.size_max = 0
        self.queue_age_sum_s = 0.0
        self.queue_age_max_s = 0.0

    def observe(self, size: int, oldest_age_s: float) -> None:
        self.batches += 1
        self.requests += size
        self.size_max = max(self.size_max, size)
        self.queue_age_sum_s += oldest_age_s
        self.queue_age_max_s = max(self.queue_age_max_s, oldest_age_s)
        _TEL_BATCH_SIZE.observe(size)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "batches": self.batches,
            "requests": self.requests,
            "size_max": self.size_max,
            "size_avg": (self.requests / self.batches) if self.batches else 0.0,
            "queue_age_avg_s": (
                self.queue_age_sum_s / self.batches if self.batches else 0.0
            ),
            "queue_age_max_s": self.queue_age_max_s,
        }


class _BatchQueue:
    """One per (replica, method): forms batches continuously.

    The pump loop never blocks on execution — it hands a formed batch to a
    spawned task (bounded by ``max_concurrent_batches``) and immediately
    starts collecting the next one, so new requests are admitted into the
    next batch while in-flight ones complete.
    """

    def __init__(
        self,
        method,
        max_batch_size: int,
        batch_wait_timeout_s: float,
        max_concurrent_batches: int,
        stats: _BatchStats,
    ):
        self._method = method
        self._max = max(1, max_batch_size)
        self._wait = max(0.0, batch_wait_timeout_s)
        self._queue: asyncio.Queue = asyncio.Queue()
        self._sem = asyncio.Semaphore(max(1, max_concurrent_batches))
        self._stats = stats
        self._pump_task = _spawn(self._pump())

    def close(self) -> None:
        self._pump_task.cancel()

    async def submit(self, value: Any) -> Any:
        loop = asyncio.get_running_loop()
        item = _BatchItem(value, loop.create_future(), loop.time())
        self._queue.put_nowait(item)
        try:
            return await item.future
        except asyncio.CancelledError:
            # Cut at the wire deadline before dispatch: the pump drops
            # cancelled futures when forming, so a dead request never
            # occupies a batch slot.
            item.future.cancel()
            raise

    def _take_live(self, item: Optional[_BatchItem]) -> Optional[_BatchItem]:
        if item is None or item.future.done():
            return None
        return item

    async def _pump(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            first = self._take_live(await self._queue.get())
            if first is None:
                continue
            batch = [first]
            start = loop.time()
            while len(batch) < self._max:
                remaining = self._wait - (loop.time() - start)
                if remaining <= 0:
                    break
                try:
                    item = self._take_live(
                        await asyncio.wait_for(
                            self._queue.get(), timeout=remaining
                        )
                    )
                except asyncio.TimeoutError:
                    break
                if item is not None:
                    batch.append(item)
            # Bound in-flight batches; formation of the next batch resumes
            # as soon as the spawn below is off our hands.
            await self._sem.acquire()
            self._stats.observe(len(batch), loop.time() - batch[0].enqueued_at)
            task = _spawn(self._run_batch(batch))
            task.add_done_callback(lambda _t: self._sem.release())

    async def _run_batch(self, batch: List[_BatchItem]) -> None:
        inputs = [item.value for item in batch]
        # Per-item queue-wait spans (enqueue -> batch launch), each parented
        # into ITS OWN request's trace; the execute span below is parented
        # to the first traced item (a span has one parent — the other
        # members' waits still link their traces to this batch).
        lead_ctx = None
        now = time.time()
        for item in batch:
            if item.trace_ctx is not None:
                if lead_ctx is None:
                    lead_ctx = item.trace_ctx
                tracing.record_span(
                    "serve.batch_wait",
                    "serve",
                    item.enqueued_wall,
                    now - item.enqueued_wall,
                    ctx=item.trace_ctx,
                )
        token = tracing.set_context(lead_ctx)
        t0 = time.time()
        try:
            if inspect.iscoroutinefunction(self._method):
                results = await self._method(inputs)
            else:
                loop = asyncio.get_running_loop()
                # copy_context AFTER the trace set: the batch's trace context
                # must follow the user method onto the executor thread.
                ctx = contextvars.copy_context()
                results = await loop.run_in_executor(
                    None, lambda: ctx.run(self._method, inputs)
                )
            if not isinstance(results, (list, tuple)) or len(results) != len(
                batch
            ):
                raise TypeError(
                    f"batched method returned "
                    f"{type(results).__name__} of length "
                    f"{len(results) if isinstance(results, (list, tuple)) else '?'}"
                    f"; expected a list of {len(batch)} results"
                )
        except Exception as e:
            for item in batch:
                if not item.future.done():
                    item.future.set_exception(e)
            return
        finally:
            tracing.reset_context(token)
            if lead_ctx is not None:
                tracing.record_span(
                    "serve.batch_execute",
                    "serve",
                    t0,
                    time.time() - t0,
                    ctx=lead_ctx,
                    size=len(batch),
                )
        for item, result in zip(batch, results):
            if not item.future.done():
                item.future.set_result(result)


class Replica:
    """The actor class the controller instantiates per replica."""

    def __init__(
        self,
        serialized_cls: bytes,
        init_args: Tuple,
        init_kwargs: Dict,
        deployment_id_str: str,
        replica_id_str: str,
        user_config: Any = None,
        max_batch_size: int = 1,
        batch_wait_timeout_s: float = 0.01,
        max_ongoing_requests: int = 16,
    ):
        cls = cloudpickle.loads(serialized_cls)
        self._deployment_id_str = deployment_id_str
        self._replica_id_str = replica_id_str
        self._num_ongoing = 0
        self._total_served = 0
        self._shutting_down = False
        self._max_batch_size = max(1, int(max_batch_size))
        self._batch_wait_timeout_s = float(batch_wait_timeout_s)
        self._max_ongoing_requests = max(1, int(max_ongoing_requests))
        self._batch_queues: Dict[str, _BatchQueue] = {}
        self._batch_stats = _BatchStats()
        if inspect.isfunction(cls):
            # Function deployments: wrap into a callable instance.
            fn = cls

            class _FnWrapper:
                def __call__(self, *a, **k):
                    return fn(*a, **k)

            self._user = _FnWrapper()
        else:
            self._user = cls(*init_args, **init_kwargs)
        if user_config is not None:
            self._apply_reconfigure(user_config)

    def _apply_reconfigure(self, user_config: Any) -> None:
        reconfigure = getattr(self._user, "reconfigure", None)
        if reconfigure is None:
            raise RuntimeError(
                "user_config was set but the deployment has no reconfigure()"
            )
        reconfigure(user_config)

    # -- data plane ----------------------------------------------------------

    def _batch_queue_for(self, method_name: str) -> _BatchQueue:
        bq = self._batch_queues.get(method_name)
        if bq is None:
            bq = _BatchQueue(
                getattr(self._user, method_name),
                self._max_batch_size,
                self._batch_wait_timeout_s,
                # Leave headroom so the next batch executes while the current
                # one is in flight, without exceeding the replica's overall
                # concurrency budget.
                max_concurrent_batches=max(
                    1, self._max_ongoing_requests // self._max_batch_size
                ),
                stats=self._batch_stats,
            )
            self._batch_queues[method_name] = bq
        return bq

    async def handle_request(
        self, request_meta: Dict[str, Any], args: Tuple, kwargs: Dict
    ) -> Any:
        """Run one request through the user callable. Called concurrently up
        to max_ongoing_requests (actor max_concurrency)."""
        self._num_ongoing += 1
        self._total_served += 1
        model_id = request_meta.get("multiplexed_model_id")
        if model_id:
            # Visible to @serve.multiplexed loaders via
            # serve.get_multiplexed_model_id() (reference: replica context).
            from ray_tpu.serve import api as serve_api

            serve_api._multiplexed_model_id_ctx.set(model_id)
        try:
            method_name = request_meta.get("call_method", "__call__")
            # Batchable shape: single positional payload, no kwargs, no
            # per-request model id (multiplexed requests must not be fused
            # across models).
            if (
                self._max_batch_size > 1
                and len(args) == 1
                and not kwargs
                and not model_id
            ):
                return await self._batch_queue_for(method_name).submit(args[0])
            method = getattr(self._user, method_name)
            if inspect.iscoroutinefunction(method):
                return await method(*args, **kwargs)
            loop = asyncio.get_running_loop()
            # copy_context: contextvars (multiplexed model id) must follow
            # the call onto the executor thread.
            ctx = contextvars.copy_context()
            return await loop.run_in_executor(
                None, lambda: ctx.run(method, *args, **kwargs)
            )
        finally:
            self._num_ongoing -= 1

    def handle_request_streaming(
        self, request_meta: Dict[str, Any], args: Tuple, kwargs: Dict
    ):
        """Streaming data plane: the user handler is a (possibly async)
        generator; each yielded item is published through the runtime's
        streaming-generator machinery as it is produced, so the proxy
        forwards chunks while the replica is still generating (reference:
        replica.py handle_request_streaming + ReportGeneratorItemReturns).

        This is a SYNC generator actor method (invoked with
        num_returns="dynamic"); it runs on the executor pool, pumping async
        generators via the worker's event loop."""
        self._num_ongoing += 1
        self._total_served += 1
        model_id = request_meta.get("multiplexed_model_id")

        def _set_model_ctx():
            # Each resume of this generator may land on a DIFFERENT executor
            # thread (every next() is its own run_in_executor dispatch), so
            # the contextvar must be re-set on the current thread before the
            # user frame runs — a single set at creation time would be lost
            # across hops and could leak onto unrelated requests.
            if model_id:
                from ray_tpu.serve import api as serve_api

                serve_api._multiplexed_model_id_ctx.set(model_id)

        try:
            _set_model_ctx()
            method_name = request_meta.get("call_method", "__call__")
            method = getattr(self._user, method_name)
            result = method(*args, **kwargs)
            if inspect.isasyncgen(result):
                from ray_tpu._private import worker as worker_mod

                loop = worker_mod._core().loop

                async def _anext():
                    _set_model_ctx()
                    return await result.__anext__()

                while True:
                    fut = asyncio.run_coroutine_threadsafe(_anext(), loop)
                    try:
                        yield fut.result()
                    except StopAsyncIteration:
                        break
            elif inspect.isgenerator(result):
                while True:
                    _set_model_ctx()
                    try:
                        item = next(result)
                    except StopIteration:
                        break
                    yield item
            elif inspect.iscoroutine(result):
                from ray_tpu._private import worker as worker_mod

                loop = worker_mod._core().loop
                yield asyncio.run_coroutine_threadsafe(result, loop).result()
            else:
                yield result
        finally:
            self._num_ongoing -= 1

    # -- control plane -------------------------------------------------------

    async def get_metrics(self) -> Dict[str, Any]:
        return {
            "replica_id": self._replica_id_str,
            "num_ongoing_requests": self._num_ongoing,
            "total_served": self._total_served,
            "batch": self._batch_stats.to_dict(),
        }

    async def check_health(self) -> bool:
        user_check = getattr(self._user, "check_health", None)
        if user_check is not None:
            if inspect.iscoroutinefunction(user_check):
                await user_check()
            else:
                user_check()
        return True

    async def reconfigure(self, user_config: Any) -> None:
        self._apply_reconfigure(user_config)

    async def prepare_for_shutdown(self, timeout_s: float = 10.0) -> None:
        """Drain: wait for ongoing requests to finish (graceful shutdown,
        reference replica.py perform_graceful_shutdown)."""
        self._shutting_down = True
        for bq in self._batch_queues.values():
            bq.close()
        deadline = asyncio.get_running_loop().time() + timeout_s
        while self._num_ongoing > 0:
            if asyncio.get_running_loop().time() > deadline:
                break
            await asyncio.sleep(0.05)
        user_del = getattr(self._user, "__del__", None)
        if user_del is not None:
            try:
                user_del()
            except Exception:
                pass
