"""Replica actor: hosts one copy of a user deployment.

Analog of python/ray/serve/_private/replica.py (ReplicaActor:231): wraps the
user callable, tracks ongoing-request count (consumed by the pow-2 router and
the autoscaler), exposes health checks and reconfigure.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
from typing import Any, Dict, Optional, Tuple

import cloudpickle

logger = logging.getLogger(__name__)


class Replica:
    """The actor class the controller instantiates per replica."""

    def __init__(
        self,
        serialized_cls: bytes,
        init_args: Tuple,
        init_kwargs: Dict,
        deployment_id_str: str,
        replica_id_str: str,
        user_config: Any = None,
    ):
        cls = cloudpickle.loads(serialized_cls)
        self._deployment_id_str = deployment_id_str
        self._replica_id_str = replica_id_str
        self._num_ongoing = 0
        self._total_served = 0
        self._shutting_down = False
        if inspect.isfunction(cls):
            # Function deployments: wrap into a callable instance.
            fn = cls

            class _FnWrapper:
                def __call__(self, *a, **k):
                    return fn(*a, **k)

            self._user = _FnWrapper()
        else:
            self._user = cls(*init_args, **init_kwargs)
        if user_config is not None:
            self._apply_reconfigure(user_config)

    def _apply_reconfigure(self, user_config: Any) -> None:
        reconfigure = getattr(self._user, "reconfigure", None)
        if reconfigure is None:
            raise RuntimeError(
                "user_config was set but the deployment has no reconfigure()"
            )
        reconfigure(user_config)

    # -- data plane ----------------------------------------------------------

    async def handle_request(
        self, request_meta: Dict[str, Any], args: Tuple, kwargs: Dict
    ) -> Any:
        """Run one request through the user callable. Called concurrently up
        to max_ongoing_requests (actor max_concurrency)."""
        self._num_ongoing += 1
        self._total_served += 1
        model_id = request_meta.get("multiplexed_model_id")
        if model_id:
            # Visible to @serve.multiplexed loaders via
            # serve.get_multiplexed_model_id() (reference: replica context).
            from ray_tpu.serve import api as serve_api

            serve_api._multiplexed_model_id_ctx.set(model_id)
        try:
            method_name = request_meta.get("call_method", "__call__")
            method = getattr(self._user, method_name)
            if inspect.iscoroutinefunction(method):
                return await method(*args, **kwargs)
            loop = asyncio.get_running_loop()
            # copy_context: contextvars (multiplexed model id) must follow
            # the call onto the executor thread.
            import contextvars

            ctx = contextvars.copy_context()
            return await loop.run_in_executor(
                None, lambda: ctx.run(method, *args, **kwargs)
            )
        finally:
            self._num_ongoing -= 1

    # -- control plane -------------------------------------------------------

    async def get_metrics(self) -> Dict[str, Any]:
        return {
            "replica_id": self._replica_id_str,
            "num_ongoing_requests": self._num_ongoing,
            "total_served": self._total_served,
        }

    async def check_health(self) -> bool:
        user_check = getattr(self._user, "check_health", None)
        if user_check is not None:
            if inspect.iscoroutinefunction(user_check):
                await user_check()
            else:
                user_check()
        return True

    async def reconfigure(self, user_config: Any) -> None:
        self._apply_reconfigure(user_config)

    async def prepare_for_shutdown(self, timeout_s: float = 10.0) -> None:
        """Drain: wait for ongoing requests to finish (graceful shutdown,
        reference replica.py perform_graceful_shutdown)."""
        self._shutting_down = True
        deadline = asyncio.get_running_loop().time() + timeout_s
        while self._num_ongoing > 0:
            if asyncio.get_running_loop().time() > deadline:
                break
            await asyncio.sleep(0.05)
        user_del = getattr(self._user, "__del__", None)
        if user_del is not None:
            try:
                user_del()
            except Exception:
                pass
