"""Replica actor: hosts one copy of a user deployment.

Analog of python/ray/serve/_private/replica.py (ReplicaActor:231): wraps the
user callable, tracks ongoing-request count (consumed by the pow-2 router and
the autoscaler), exposes health checks and reconfigure.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
from typing import Any, Dict, Optional, Tuple

import cloudpickle

logger = logging.getLogger(__name__)


class Replica:
    """The actor class the controller instantiates per replica."""

    def __init__(
        self,
        serialized_cls: bytes,
        init_args: Tuple,
        init_kwargs: Dict,
        deployment_id_str: str,
        replica_id_str: str,
        user_config: Any = None,
    ):
        cls = cloudpickle.loads(serialized_cls)
        self._deployment_id_str = deployment_id_str
        self._replica_id_str = replica_id_str
        self._num_ongoing = 0
        self._total_served = 0
        self._shutting_down = False
        if inspect.isfunction(cls):
            # Function deployments: wrap into a callable instance.
            fn = cls

            class _FnWrapper:
                def __call__(self, *a, **k):
                    return fn(*a, **k)

            self._user = _FnWrapper()
        else:
            self._user = cls(*init_args, **init_kwargs)
        if user_config is not None:
            self._apply_reconfigure(user_config)

    def _apply_reconfigure(self, user_config: Any) -> None:
        reconfigure = getattr(self._user, "reconfigure", None)
        if reconfigure is None:
            raise RuntimeError(
                "user_config was set but the deployment has no reconfigure()"
            )
        reconfigure(user_config)

    # -- data plane ----------------------------------------------------------

    async def handle_request(
        self, request_meta: Dict[str, Any], args: Tuple, kwargs: Dict
    ) -> Any:
        """Run one request through the user callable. Called concurrently up
        to max_ongoing_requests (actor max_concurrency)."""
        self._num_ongoing += 1
        self._total_served += 1
        model_id = request_meta.get("multiplexed_model_id")
        if model_id:
            # Visible to @serve.multiplexed loaders via
            # serve.get_multiplexed_model_id() (reference: replica context).
            from ray_tpu.serve import api as serve_api

            serve_api._multiplexed_model_id_ctx.set(model_id)
        try:
            method_name = request_meta.get("call_method", "__call__")
            method = getattr(self._user, method_name)
            if inspect.iscoroutinefunction(method):
                return await method(*args, **kwargs)
            loop = asyncio.get_running_loop()
            # copy_context: contextvars (multiplexed model id) must follow
            # the call onto the executor thread.
            import contextvars

            ctx = contextvars.copy_context()
            return await loop.run_in_executor(
                None, lambda: ctx.run(method, *args, **kwargs)
            )
        finally:
            self._num_ongoing -= 1

    def handle_request_streaming(
        self, request_meta: Dict[str, Any], args: Tuple, kwargs: Dict
    ):
        """Streaming data plane: the user handler is a (possibly async)
        generator; each yielded item is published through the runtime's
        streaming-generator machinery as it is produced, so the proxy
        forwards chunks while the replica is still generating (reference:
        replica.py handle_request_streaming + ReportGeneratorItemReturns).

        This is a SYNC generator actor method (invoked with
        num_returns="dynamic"); it runs on the executor pool, pumping async
        generators via the worker's event loop."""
        self._num_ongoing += 1
        self._total_served += 1
        model_id = request_meta.get("multiplexed_model_id")

        def _set_model_ctx():
            # Each resume of this generator may land on a DIFFERENT executor
            # thread (every next() is its own run_in_executor dispatch), so
            # the contextvar must be re-set on the current thread before the
            # user frame runs — a single set at creation time would be lost
            # across hops and could leak onto unrelated requests.
            if model_id:
                from ray_tpu.serve import api as serve_api

                serve_api._multiplexed_model_id_ctx.set(model_id)

        try:
            _set_model_ctx()
            method_name = request_meta.get("call_method", "__call__")
            method = getattr(self._user, method_name)
            result = method(*args, **kwargs)
            if inspect.isasyncgen(result):
                from ray_tpu._private import worker as worker_mod

                loop = worker_mod._core().loop

                async def _anext():
                    _set_model_ctx()
                    return await result.__anext__()

                while True:
                    fut = asyncio.run_coroutine_threadsafe(_anext(), loop)
                    try:
                        yield fut.result()
                    except StopAsyncIteration:
                        break
            elif inspect.isgenerator(result):
                while True:
                    _set_model_ctx()
                    try:
                        item = next(result)
                    except StopIteration:
                        break
                    yield item
            elif inspect.iscoroutine(result):
                from ray_tpu._private import worker as worker_mod

                loop = worker_mod._core().loop
                yield asyncio.run_coroutine_threadsafe(result, loop).result()
            else:
                yield result
        finally:
            self._num_ongoing -= 1

    # -- control plane -------------------------------------------------------

    async def get_metrics(self) -> Dict[str, Any]:
        return {
            "replica_id": self._replica_id_str,
            "num_ongoing_requests": self._num_ongoing,
            "total_served": self._total_served,
        }

    async def check_health(self) -> bool:
        user_check = getattr(self._user, "check_health", None)
        if user_check is not None:
            if inspect.iscoroutinefunction(user_check):
                await user_check()
            else:
                user_check()
        return True

    async def reconfigure(self, user_config: Any) -> None:
        self._apply_reconfigure(user_config)

    async def prepare_for_shutdown(self, timeout_s: float = 10.0) -> None:
        """Drain: wait for ongoing requests to finish (graceful shutdown,
        reference replica.py perform_graceful_shutdown)."""
        self._shutting_down = True
        deadline = asyncio.get_running_loop().time() + timeout_s
        while self._num_ongoing > 0:
            if asyncio.get_running_loop().time() > deadline:
                break
            await asyncio.sleep(0.05)
        user_del = getattr(self._user, "__del__", None)
        if user_del is not None:
            try:
                user_del()
            except Exception:
                pass
