"""Shared Serve types: IDs, statuses, request metadata.

Analog of the reference's python/ray/serve/_private/common.py (DeploymentID,
ReplicaID, DeploymentStatus, ApplicationStatus, RequestMetadata).
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, Optional

SERVE_NAMESPACE = "serve"
CONTROLLER_NAME = "SERVE_CONTROLLER"
DEFAULT_APP_NAME = "default"


class DeploymentOverloadedError(Exception):
    """Typed load-shed: the router refused this request at admission.

    ``reason`` is one of:

    - ``"queue_full"`` — the deployment's router queue is at its
      max_queued_requests cap; admitting more would grow memory without
      bound under an open-loop storm.
    - ``"deadline_unreachable"`` — the request's remaining deadline budget
      cannot cover the observed per-replica service estimate, so running it
      would burn a replica slot only to be cut at the wire deadline.

    Callers (proxy, loadgen, chaos) treat this as backpressure, not a bug:
    the HTTP proxy maps it to 503, gRPC to RESOURCE_EXHAUSTED.
    """

    def __init__(self, deployment_id_str: str, reason: str, detail: str = ""):
        self.deployment_id_str = deployment_id_str
        self.reason = reason
        super().__init__(
            f"deployment {deployment_id_str} overloaded ({reason})"
            + (f": {detail}" if detail else "")
        )


@dataclass(frozen=True)
class DeploymentID:
    name: str
    app_name: str = DEFAULT_APP_NAME

    def __str__(self) -> str:
        return f"{self.app_name}#{self.name}"

    @classmethod
    def parse(cls, s: str) -> "DeploymentID":
        app, _, name = s.partition("#")
        return cls(name=name, app_name=app)


@dataclass(frozen=True)
class ReplicaID:
    unique_id: str
    deployment_id: DeploymentID

    @classmethod
    def generate(cls, deployment_id: DeploymentID) -> "ReplicaID":
        return cls(unique_id=uuid.uuid4().hex[:8], deployment_id=deployment_id)

    def to_actor_name(self) -> str:
        d = self.deployment_id
        return f"SERVE_REPLICA::{d.app_name}#{d.name}#{self.unique_id}"


class DeploymentStatus(str, Enum):
    UPDATING = "UPDATING"
    HEALTHY = "HEALTHY"
    UNHEALTHY = "UNHEALTHY"
    UPSCALING = "UPSCALING"
    DOWNSCALING = "DOWNSCALING"
    DELETING = "DELETING"


class ApplicationStatus(str, Enum):
    NOT_STARTED = "NOT_STARTED"
    DEPLOYING = "DEPLOYING"
    RUNNING = "RUNNING"
    DEPLOY_FAILED = "DEPLOY_FAILED"
    DELETING = "DELETING"
    UNHEALTHY = "UNHEALTHY"


class ReplicaState(str, Enum):
    STARTING = "STARTING"
    RUNNING = "RUNNING"
    STOPPING = "STOPPING"


@dataclass
class RequestMetadata:
    """Per-request routing metadata (reference: serve/_private/common.py
    RequestMetadata)."""

    request_id: str = field(default_factory=lambda: uuid.uuid4().hex)
    call_method: str = "__call__"
    route: str = ""
    multiplexed_model_id: str = ""
    is_http_request: bool = False
    http_method: str = "GET"
    headers: Dict[str, str] = field(default_factory=dict)


@dataclass
class DeploymentStatusInfo:
    name: str
    status: DeploymentStatus
    message: str = ""
    replica_states: Dict[str, int] = field(default_factory=dict)


@dataclass
class ApplicationStatusInfo:
    name: str
    status: ApplicationStatus
    message: str = ""
    route_prefix: Optional[str] = None
    deployments: Dict[str, DeploymentStatusInfo] = field(default_factory=dict)


@dataclass
class RunningReplicaInfo:
    """What routers need to know about a live replica."""

    replica_id_str: str
    deployment_id_str: str
    actor_id: str
    max_ongoing_requests: int
    # Router queue cap for the whole deployment (-1 -> the
    # config.serve_max_queued_requests default); rides the replica-set
    # long-poll push so routers learn it without extra RPCs.
    max_queued_requests: int = -1

    def to_dict(self) -> Dict[str, Any]:
        return {
            "replica_id_str": self.replica_id_str,
            "deployment_id_str": self.deployment_id_str,
            "actor_id": self.actor_id,
            "max_ongoing_requests": self.max_ongoing_requests,
            "max_queued_requests": self.max_queued_requests,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RunningReplicaInfo":
        return cls(**d)
