"""Long-poll config fan-out: controller hosts, routers/proxies listen.

Analog of python/ray/serve/_private/long_poll.py (LongPollHost:173,
LongPollClient): listeners send {key: last_seen_snapshot_id}; the host
replies as soon as any key has a newer snapshot, so config changes (replica
sets, route tables) propagate without polling on the data path.
"""

from __future__ import annotations

import asyncio

from ray_tpu._private.common import config
from ray_tpu._private.rpc import spawn as _spawn
from typing import Any, Callable, Dict, Optional, Tuple


class LongPollHost:
    """Lives inside the ServeController actor."""

    def __init__(self):
        self._snapshot_ids: Dict[str, int] = {}
        self._snapshots: Dict[str, Any] = {}
        self._changed = asyncio.Condition()

    def notify_changed(self, key: str, value: Any) -> None:
        self._snapshot_ids[key] = self._snapshot_ids.get(key, -1) + 1
        self._snapshots[key] = value

        async def _wake():
            async with self._changed:
                self._changed.notify_all()

        _spawn(_wake())

    async def listen_for_change(
        self, keys_to_snapshot_ids: Dict[str, int]
    ) -> Dict[str, Tuple[int, Any]]:
        """Block until any requested key is newer than the caller's snapshot,
        then return {key: (snapshot_id, value)} for all stale keys."""

        def stale() -> Dict[str, Tuple[int, Any]]:
            out = {}
            for key, seen in keys_to_snapshot_ids.items():
                cur = self._snapshot_ids.get(key, -1)
                if cur > seen:
                    out[key] = (cur, self._snapshots.get(key))
            return out

        out = stale()
        if out:
            return out
        try:
            async with self._changed:
                await asyncio.wait_for(
                    self._changed.wait_for(lambda: bool(stale())),
                    timeout=config.serve_long_poll_timeout_s,
                )
        except asyncio.TimeoutError:
            return {}
        return stale()


class LongPollClient:
    """Runs wherever a router lives; re-issues listen calls forever and feeds
    updates to callbacks. `listen` is an async callable
    (keys_to_snapshot_ids) -> updates dict."""

    def __init__(
        self,
        listen: Callable,
        key_listeners: Dict[str, Callable[[Any], None]],
    ):
        self._listen = listen
        self._key_listeners = key_listeners
        self._snapshot_ids = {k: -1 for k in key_listeners}
        self._task: Optional[asyncio.Task] = None
        self._stopped = False

    def start(self) -> None:
        self._task = _spawn(self._run())

    def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()

    async def _run(self) -> None:
        while not self._stopped:
            try:
                updates = await self._listen(dict(self._snapshot_ids))
            except asyncio.CancelledError:
                return
            except Exception:
                await asyncio.sleep(0.2)
                continue
            for key, (sid, value) in (updates or {}).items():
                # Single-writer: _run() is the only task that mutates this
                # client's _snapshot_ids, so the read-await-write is benign.
                self._snapshot_ids[key] = sid  # aio-lint: disable=await-interleave
                cb = self._key_listeners.get(key)
                if cb is not None:
                    cb(value)
