"""HTTP ingress proxy actor.

Analog of python/ray/serve/_private/proxy.py (ProxyActor): an aiohttp server
inside an async actor. Routes by longest matching route prefix (route table
pushed from the controller via long-poll), then hands the request to the
ingress deployment through the shared pow-2 Router. The controller is never
on the request path.
"""

from __future__ import annotations

import asyncio
import json
import logging
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from ray_tpu._private import rpc
from ray_tpu._private import worker as worker_mod
from ray_tpu._private.common import config
from ray_tpu.actor import ActorHandle
from ray_tpu.serve._private.common import DeploymentOverloadedError
from ray_tpu.serve._private.long_poll import LongPollClient

logger = logging.getLogger(__name__)


@dataclass
class HTTPRequest:
    """Picklable request passed to ingress deployments (stand-in for the
    reference's starlette Request)."""

    method: str = "GET"
    path: str = "/"
    query: Dict[str, str] = field(default_factory=dict)
    headers: Dict[str, str] = field(default_factory=dict)
    body: bytes = b""

    def json(self) -> Any:
        return json.loads(self.body or b"null")

    def text(self) -> str:
        return (self.body or b"").decode()


def _to_response(result: Any) -> Tuple[int, bytes, str]:
    if isinstance(result, tuple) and len(result) == 2 and isinstance(result[0], int):
        status, body = result
        _, b, ct = _to_response(body)
        return status, b, ct
    if isinstance(result, bytes):
        return 200, result, "application/octet-stream"
    if isinstance(result, str):
        return 200, result.encode(), "text/plain; charset=utf-8"
    return 200, json.dumps(result).encode(), "application/json"


class ProxyActor:
    def __init__(
        self, host: str = "127.0.0.1", port: int = 8000, grpc_port=None
    ):
        self._host = host
        self._port = port
        self._grpc_port = grpc_port
        self._grpc_server = None
        self._route_table: Dict[str, Dict[str, str]] = {}
        self._router = None
        self._runner = None
        self._poll: Optional[LongPollClient] = None

    async def _get_controller_handle(self) -> ActorHandle:
        core = worker_mod._core()
        reply = await core.gcs.call(
            "GetNamedActor", {"name": "SERVE_CONTROLLER", "namespace": "serve"}
        )
        return ActorHandle(reply["actor"]["actor_id"])

    async def ready(self) -> Dict[str, Any]:
        """Bind the HTTP server; returns the bound address."""
        if self._runner is not None:
            return {"host": self._host, "port": self._port}
        from aiohttp import web

        from ray_tpu.serve._private.router import Router

        core = worker_mod._core()
        controller = await self._get_controller_handle()
        self._router = Router(controller, core)

        async def listen(keys_to_ids):
            refs = await core.submit_actor_task(
                controller._actor_id,
                "listen_for_change",
                (keys_to_ids,),
                {},
                num_returns=1,
            )
            return await core.get_objects(refs[0], timeout=None)

        self._poll = LongPollClient(
            listen, {"route_table": self._set_route_table}
        )
        self._poll.start()

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self._host, self._port)
        await site.start()
        if self._port == 0:
            self._port = site._server.sockets[0].getsockname()[1]
        if self._grpc_port is not None:
            await self._start_grpc()
        return {
            "host": self._host,
            "port": self._port,
            "grpc_port": self._grpc_port,
        }

    def _grpc_target(self, app: str):
        """Resolve a ServeRequest.application to a deployment id string."""
        for _, t in sorted(self._route_table.items()):
            if not app or t["app"] == app:
                return f"{t['app']}#{t['ingress']}"
        return None

    @staticmethod
    def _encode_reply(result: Any):
        """-> (payload bytes, content_type tag) for a ServeReply."""
        if isinstance(result, bytes):
            return result, "bytes"
        if isinstance(result, str):
            return result.encode(), "text"
        try:
            return json.dumps(result).encode(), "json"
        except (TypeError, ValueError):
            import cloudpickle

            return cloudpickle.dumps(result), "pickle"

    async def _start_grpc(self) -> None:
        """Typed gRPC ingress (reference: serve.proto RayServeAPIService):
        /ray_tpu.serve.ServeAPIService/Predict (unary) and /PredictStreaming
        (server-streaming), with ServeRequest carrying application, handler
        method, multiplexed model id, and the payload."""
        import grpc

        from ray_tpu.serve.protobuf import (
            ServeReply,
            add_serve_api_servicer,
        )

        def _meta(request):
            return {
                "call_method": request.method or "__call__",
                "multiplexed_model_id": request.multiplexed_model_id or None,
            }

        async def predict(request, context) -> "ServeReply":
            dep_id_str = self._grpc_target(request.application)
            if dep_id_str is None:
                await context.abort(
                    grpc.StatusCode.NOT_FOUND,
                    f"no serve application {request.application!r}",
                )
            try:
                result = await self._router.assign_request(
                    dep_id_str, _meta(request), (request.payload,), {},
                    timeout_s=config.serve_request_timeout_s,
                )
            except DeploymentOverloadedError as e:
                await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
            except (TimeoutError, rpc.DeadlineExceeded) as e:
                await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
            except Exception as e:
                await context.abort(
                    grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}"
                )
            payload, ctype = self._encode_reply(result)
            return ServeReply(payload=payload, content_type=ctype)

        async def predict_streaming(request, context):
            dep_id_str = self._grpc_target(request.application)
            if dep_id_str is None:
                await context.abort(
                    grpc.StatusCode.NOT_FOUND,
                    f"no serve application {request.application!r}",
                )
            try:
                async for item in self._router.assign_request_streaming(
                    dep_id_str, _meta(request), (request.payload,), {},
                    timeout_s=config.serve_request_timeout_s,
                ):
                    payload, ctype = self._encode_reply(item)
                    yield ServeReply(payload=payload, content_type=ctype)
            except DeploymentOverloadedError as e:
                await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
            except (TimeoutError, rpc.DeadlineExceeded) as e:
                await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
            except Exception as e:
                await context.abort(
                    grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}"
                )

        self._grpc_server = grpc.aio.server()
        add_serve_api_servicer(self._grpc_server, predict, predict_streaming)
        bound = self._grpc_server.add_insecure_port(
            f"{self._host}:{self._grpc_port}"
        )
        await self._grpc_server.start()
        self._grpc_port = bound

    @staticmethod
    def _request_budget(request) -> float:
        """Per-request deadline budget: clients may shrink (or stretch) the
        default via the serve-request-timeout-s header."""
        raw = request.headers.get("serve-request-timeout-s")
        if raw:
            try:
                return max(0.001, float(raw))
            except ValueError:
                pass
        return config.serve_request_timeout_s

    def _set_route_table(self, table: Dict[str, Dict[str, str]]) -> None:
        self._route_table = table or {}

    def _match_route(self, path: str) -> Optional[Tuple[str, Dict[str, str]]]:
        best = None
        for prefix, target in self._route_table.items():
            norm = prefix.rstrip("/") or "/"
            if path == norm or path.startswith(norm if norm != "/" else "/"):
                if norm != "/" and not (
                    path == norm or path[len(norm) :].startswith("/")
                ):
                    continue
                if best is None or len(norm) > len(best[0]):
                    best = (norm, target)
        return best

    async def _handle(self, request):
        from aiohttp import web

        path = request.path
        if path == "/-/healthz":
            return web.Response(text="success")
        if path == "/-/routes":
            return web.json_response(
                {p: t["app"] for p, t in self._route_table.items()}
            )
        match = self._match_route(path)
        if match is None:
            return web.Response(status=404, text=f"no route for {path}")
        prefix, target = match
        dep_id_str = f"{target['app']}#{target['ingress']}"
        body = await request.read()
        http_req = HTTPRequest(
            method=request.method,
            path=path[len(prefix) :] if prefix != "/" else path,
            query=dict(request.query),
            headers=dict(request.headers),
            body=body,
        )
        meta = {
            "call_method": "__call__",
            "is_http_request": True,
            # Reference Serve convention: multiplexed model id rides
            # an HTTP header.
            "multiplexed_model_id": request.headers.get(
                "serve_multiplexed_model_id", ""
            ),
        }
        # Streaming response modes (reference: StreamingResponse from a
        # generator deployment + the fastapi SSE integration):
        #   Accept: text/event-stream  -> standards-compliant SSE framing
        #     (each yielded item becomes one `data:` event; EventSource
        #     clients work unmodified);
        #   serve-streaming header    -> raw chunked bytes (legacy opt-in
        #     for binary streams).
        accept = request.headers.get("Accept", "")
        if "text/event-stream" in accept:
            return await self._handle_streaming(
                request, dep_id_str, meta, http_req, sse=True
            )
        if request.headers.get("serve-streaming"):
            return await self._handle_streaming(
                request, dep_id_str, meta, http_req
            )
        try:
            result = await self._router.assign_request(
                dep_id_str, meta, (http_req,), {},
                timeout_s=self._request_budget(request),
            )
        except DeploymentOverloadedError as e:
            # Typed shed -> 503 with Retry-After: the client should back
            # off, the deployment is refusing (not failing) the request.
            return web.Response(
                status=503, text=str(e), headers={"Retry-After": "1"}
            )
        except rpc.DeadlineExceeded as e:
            return web.Response(status=504, text=str(e))
        except TimeoutError as e:
            return web.Response(status=503, text=str(e))
        except Exception as e:
            logger.warning("request to %s failed: %r", dep_id_str, e)
            return web.Response(status=500, text=f"{type(e).__name__}: {e}")
        status, payload, ctype = _to_response(result)
        return web.Response(status=status, body=payload, content_type=ctype.split(";")[0])

    @staticmethod
    def _sse_frame(item) -> bytes:
        """One server-sent event per yielded item. Multi-line payloads get
        one `data:` line each (SSE spec: consecutive data lines join with
        newline on the client)."""
        if isinstance(item, bytes):
            text = item.decode("utf-8", "replace")
        elif isinstance(item, str):
            text = item
        else:
            text = json.dumps(item)
        lines = text.split("\n")
        return ("".join(f"data: {ln}\n" for ln in lines) + "\n").encode()

    async def _handle_streaming(
        self, request, dep_id_str, meta, http_req, sse: bool = False
    ):
        """Streamed HTTP response: each item the replica's generator yields
        is written as soon as it arrives. sse=True uses text/event-stream
        framing (Accept-negotiated); otherwise raw chunks (bytes as-is,
        str utf-8, other values JSON + newline)."""
        from aiohttp import web

        headers = (
            {
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
            }
            if sse
            else {"Content-Type": "application/octet-stream"}
        )
        resp = web.StreamResponse(status=200, headers=headers)
        started = False
        try:
            async for item in self._router.assign_request_streaming(
                dep_id_str, meta, (http_req,), {},
                timeout_s=self._request_budget(request),
            ):
                if not started:
                    await resp.prepare(request)
                    started = True
                if sse:
                    chunk = self._sse_frame(item)
                elif isinstance(item, bytes):
                    chunk = item
                elif isinstance(item, str):
                    chunk = item.encode()
                else:
                    chunk = json.dumps(item).encode() + b"\n"
                await resp.write(chunk)
        except DeploymentOverloadedError as e:
            if not started:
                return web.Response(
                    status=503, text=str(e), headers={"Retry-After": "1"}
                )
            raise
        except TimeoutError as e:
            if not started:
                return web.Response(status=503, text=str(e))
            raise  # mid-stream: the broken body tells the client
        except Exception as e:
            logger.warning("streaming request to %s failed: %r", dep_id_str, e)
            if not started:
                return web.Response(
                    status=500, text=f"{type(e).__name__}: {e}"
                )
            raise
        if not started:
            await resp.prepare(request)
        await resp.write_eof()
        return resp

    async def check_health(self) -> bool:
        return True
