"""ServeController: the singleton control-plane actor.

Analog of python/ray/serve/_private/controller.py (ServeController:86) +
application_state.py / deployment_state.py: holds target state per
application/deployment, runs a reconciliation loop that starts/stops/heals
replica actors, autoscales on queue metrics, and fans config out to routers
and proxies via a long-poll host. The data plane never touches the controller.
"""

from __future__ import annotations

import asyncio
import math

from ray_tpu._private.rpc import spawn as _spawn
import logging
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu._private import telemetry
from ray_tpu._private import worker as worker_mod
from ray_tpu._private.common import config as _config
from ray_tpu._private.gcs import DEAD as ACTOR_DEAD
from ray_tpu.serve._private.common import (
    ApplicationStatus,
    DeploymentID,
    DeploymentStatus,
    ReplicaID,
    RunningReplicaInfo,
)
from ray_tpu.serve._private.long_poll import LongPollHost
from ray_tpu.serve.schema import AutoscalingConfig, DeploymentConfig

logger = logging.getLogger(__name__)

RECONCILE_PERIOD_S = 0.25


class _ReplicaRecord:
    def __init__(
        self,
        replica_id: ReplicaID,
        actor_id: str,
        max_ongoing: int,
        max_queued: int = -1,
    ):
        self.replica_id = replica_id
        self.actor_id = actor_id
        self.max_ongoing = max_ongoing
        self.max_queued = max_queued
        self.ready = False
        self.health_task: Optional[asyncio.Task] = None
        self.consecutive_health_failures = 0
        # GCS actor:<id> pubsub handler while the death watch is armed.
        self.death_watch: Optional[Any] = None

    def info(self) -> RunningReplicaInfo:
        return RunningReplicaInfo(
            replica_id_str=self.replica_id.unique_id,
            deployment_id_str=str(self.replica_id.deployment_id),
            actor_id=self.actor_id,
            max_ongoing_requests=self.max_ongoing,
            max_queued_requests=self.max_queued,
        )


class _DeploymentState:
    """Target + actual state for one deployment (reference
    deployment_state.py DeploymentState)."""

    def __init__(self, dep_id: DeploymentID, spec: Dict[str, Any]):
        self.dep_id = dep_id
        self.spec = spec
        self.config = DeploymentConfig.from_dict(spec["config"])
        self.replicas: Dict[str, _ReplicaRecord] = {}
        self.starting: Dict[str, asyncio.Task] = {}
        self.stopping: Dict[str, asyncio.Task] = {}
        self.status = DeploymentStatus.UPDATING
        self.message = ""
        self.deleting = False
        # autoscaling bookkeeping
        self.metrics_window: List[tuple] = []  # (t, total_ongoing)
        self.queue_ewma = 0.0  # smoothed router queue depth
        self.above_since: Optional[float] = None  # hysteresis timers
        self.below_since: Optional[float] = None
        self.current_target: Optional[int] = None
        # start-failure backoff
        self.consecutive_start_failures = 0
        self.backoff_until = 0.0

    @property
    def target_replicas(self) -> int:
        if self.deleting:
            return 0
        ac = self.config.autoscaling_config
        if ac is not None:
            if self.current_target is None:
                self.current_target = max(ac.min_replicas, 1)
            return self.current_target
        return self.config.num_replicas

    def running_infos(self) -> List[RunningReplicaInfo]:
        return [r.info() for r in self.replicas.values() if r.ready]


def autoscale_tick(state: _DeploymentState, ac: AutoscalingConfig, now: float):
    """Decide the replica target from the ongoing-request window plus the
    smoothed router queue depth (state.queue_ewma), with hysteresis: a
    desired target only takes effect after it has held continuously for
    upscale_delay_s / downscale_delay_s. Returns the new target, or None.

    Kept as a free function (its only side effects are the window prune and
    the hysteresis timers on `state`) so tests can drive it with synthetic
    clocks and queue depths without a live control loop.
    """
    window = [
        (t, v) for (t, v) in state.metrics_window if now - t <= ac.look_back_period_s
    ]
    state.metrics_window = window
    if not window:
        return None
    ongoing_avg = sum(v for _, v in window) / len(window)
    # Queued requests are load the replicas haven't absorbed yet; counting
    # them is what makes the scaler react to saturation (ongoing alone
    # plateaus at num_replicas * max_ongoing_requests under overload).
    load = ongoing_avg + state.queue_ewma
    desired = max(
        ac.min_replicas,
        min(ac.max_replicas, math.ceil(load / max(ac.target_ongoing_requests, 1e-9))),
    )
    cur = state.target_replicas
    if desired > cur:
        state.below_since = None
        if state.above_since is None:
            state.above_since = now
        if now - state.above_since >= ac.upscale_delay_s:
            state.above_since = None
            return desired
    elif desired < cur:
        state.above_since = None
        if state.below_since is None:
            state.below_since = now
        if now - state.below_since >= ac.downscale_delay_s:
            state.below_since = None
            return desired
    else:
        state.above_since = None
        state.below_since = None
    return None


_TEL_AUTOSCALE = telemetry.counter(
    "serve", "autoscale_decisions",
    "autoscaler target changes that survived hysteresis",
)


class ServeController:
    """Created as a detached named actor with high max_concurrency so
    long-poll listens don't block control operations."""

    def __init__(self, http_options: Optional[Dict[str, Any]] = None):
        self._http_options = http_options or {}
        self._apps: Dict[str, Dict[str, Any]] = {}  # app -> app spec + status
        self._deployments: Dict[str, _DeploymentState] = {}  # str(dep_id) -> state
        self._long_poll = LongPollHost()
        self._loop_task: Optional[asyncio.Task] = None
        self._proxy_actor_id: Optional[str] = None
        self._shutdown = False
        # (dep_id_str, router_id) -> (monotonic ts, queued, ongoing); pushed
        # by every router's metrics loop, consumed by the autoscaler.
        self._router_metrics: Dict[Tuple[str, str], Tuple[float, int, int]] = {}

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> bool:
        if self._loop_task is None:
            self._loop_task = _spawn(self._run_control_loop())
            if self._http_options.get("enabled", True):
                await self._ensure_proxy()
        return True

    async def _ensure_proxy(self) -> None:
        if self._proxy_actor_id is not None:
            return
        from ray_tpu.serve._private.proxy import ProxyActor

        core = worker_mod._core()
        actor_id = await core.create_actor(
            cloudpickle.dumps(ProxyActor),
            "ServeProxy",
            (
                self._http_options.get("host", "127.0.0.1"),
                self._http_options.get("port", 8000),
                self._http_options.get("grpc_port"),
            ),
            {},
            resources={"CPU": 0.0},
            max_concurrency=1000,
            name="SERVE_PROXY",
            namespace="serve",
            lifetime="detached",
        )
        self._proxy_actor_id = actor_id
        # Tell the proxy to bind its HTTP server.
        refs = await core.submit_actor_task(actor_id, "ready", (), {}, num_returns=1)
        bound = await core.get_objects(refs[0], timeout=None)
        self._http_options["port"] = bound["port"]
        if bound.get("grpc_port") is not None:
            self._http_options["grpc_port"] = bound["grpc_port"]
        logger.info("serve proxy listening on %s", bound)

    async def get_http_config(self) -> Dict[str, Any]:
        return dict(self._http_options)

    async def check_alive(self) -> bool:
        return True

    async def record_router_metrics(
        self, router_id: str, snap: Dict[str, Dict[str, int]]
    ) -> None:
        """Routers push {dep_id_str: {"queued": n, "ongoing": n}} here on a
        short interval; the autoscaler sums fresh entries across routers."""
        now = time.monotonic()
        for dep_key, m in (snap or {}).items():
            self._router_metrics[(dep_key, router_id)] = (
                now,
                int(m.get("queued", 0)),
                int(m.get("ongoing", 0)),
            )

    # -- long poll -----------------------------------------------------------

    async def listen_for_change(self, keys_to_snapshot_ids: Dict[str, int]):
        return await self._long_poll.listen_for_change(keys_to_snapshot_ids)

    def _broadcast_replicas(self, dep_id_str: str) -> None:
        state = self._deployments.get(dep_id_str)
        infos = [] if state is None else [i.to_dict() for i in state.running_infos()]
        self._long_poll.notify_changed(f"replicas::{dep_id_str}", infos)

    def _broadcast_routes(self) -> None:
        table = {}
        for app_name, app in self._apps.items():
            if app.get("route_prefix") and app["status"] in (
                ApplicationStatus.RUNNING,
                ApplicationStatus.DEPLOYING,
            ):
                table[app["route_prefix"]] = {
                    "app": app_name,
                    "ingress": app["ingress"],
                }
        self._long_poll.notify_changed("route_table", table)

    # -- deploy / delete API -------------------------------------------------

    async def deploy_application(self, app_spec: Dict[str, Any]) -> None:
        """app_spec: {name, route_prefix, ingress, deployments: [dep_spec]}.
        dep_spec: {name, serialized_cls, init_args_blob, config}."""
        name = app_spec["name"]
        old = self._apps.get(name)
        if old is not None:
            # Redeploy: drop deployments no longer present.
            new_names = {d["name"] for d in app_spec["deployments"]}
            for dep in old["deployments"]:
                if dep not in new_names:
                    key = str(DeploymentID(dep, name))
                    if key in self._deployments:
                        self._deployments[key].deleting = True
        self._apps[name] = {
            "name": name,
            "route_prefix": app_spec.get("route_prefix"),
            "ingress": app_spec.get("ingress"),
            "deployments": [d["name"] for d in app_spec["deployments"]],
            "status": ApplicationStatus.DEPLOYING,
            "message": "",
        }
        for dep_spec in app_spec["deployments"]:
            dep_id = DeploymentID(dep_spec["name"], name)
            key = str(dep_id)
            existing = self._deployments.get(key)
            if existing is not None and not existing.deleting:
                # In-place update: new config; replicas restart only if the
                # code/init args changed (version hash).
                if existing.spec.get("version") == dep_spec.get("version"):
                    old_cfg = existing.config
                    existing.spec = dep_spec
                    existing.config = DeploymentConfig.from_dict(dep_spec["config"])
                    existing.current_target = None
                    existing.status = DeploymentStatus.UPDATING
                    # Lightweight (same-code) config change: push user_config
                    # to live replicas and refresh router-visible limits.
                    new_cfg = existing.config
                    for rec in existing.replicas.values():
                        rec.max_ongoing = new_cfg.max_ongoing_requests
                        rec.max_queued = new_cfg.max_queued_requests
                    if new_cfg.user_config != old_cfg.user_config:
                        _spawn(
                            self._reconfigure_replicas(existing, new_cfg.user_config)
                        )
                    self._broadcast_replicas(key)
                    continue
                for rec in list(existing.replicas.values()):
                    self._start_stopping(existing, rec)
                existing.spec = dep_spec
                existing.config = DeploymentConfig.from_dict(dep_spec["config"])
                existing.current_target = None
                existing.status = DeploymentStatus.UPDATING
            else:
                self._deployments[key] = _DeploymentState(dep_id, dep_spec)
        self._broadcast_routes()

    async def delete_application(self, name: str) -> None:
        app = self._apps.get(name)
        if app is None:
            return
        app["status"] = ApplicationStatus.DELETING
        for dep in app["deployments"]:
            key = str(DeploymentID(dep, name))
            if key in self._deployments:
                self._deployments[key].deleting = True
        self._broadcast_routes()

    async def graceful_shutdown(self) -> None:
        # Mark everything deleting and let the reconcile loop (still running)
        # drain and kill replicas; only then stop the loop.
        for app in self._apps.values():
            app["status"] = ApplicationStatus.DELETING
        for state in self._deployments.values():
            state.deleting = True
        self._broadcast_routes()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if not self._deployments:
                break
            await asyncio.sleep(0.1)
        self._shutdown = True
        core = worker_mod._core()
        if self._proxy_actor_id:
            try:
                await core.kill_actor(self._proxy_actor_id)
            except Exception:
                pass

    # -- status --------------------------------------------------------------

    async def get_serve_status(self) -> Dict[str, Any]:
        out = {}
        for app_name, app in self._apps.items():
            deps = {}
            for dep in app["deployments"]:
                state = self._deployments.get(str(DeploymentID(dep, app_name)))
                if state is None:
                    continue
                counts = {
                    "RUNNING": sum(1 for r in state.replicas.values() if r.ready),
                    "STARTING": len(state.starting)
                    + sum(1 for r in state.replicas.values() if not r.ready),
                    "STOPPING": len(state.stopping),
                }
                deps[dep] = {
                    "status": state.status.value,
                    "message": state.message,
                    "replica_states": counts,
                    "target_replicas": state.target_replicas,
                }
            out[app_name] = {
                "status": app["status"].value
                if isinstance(app["status"], ApplicationStatus)
                else app["status"],
                "message": app.get("message", ""),
                "route_prefix": app.get("route_prefix"),
                "ingress": app.get("ingress"),
                "deployments": deps,
            }
        return out

    # -- reconciliation ------------------------------------------------------

    async def _run_control_loop(self) -> None:
        while not self._shutdown:
            try:
                await self._reconcile_once()
            except Exception:
                logger.error("reconcile error:\n%s", traceback.format_exc())
            await asyncio.sleep(RECONCILE_PERIOD_S)

    async def _reconcile_once(self) -> None:
        for key, state in list(self._deployments.items()):
            self._autoscale(state)
            target = state.target_replicas
            actual = len(state.replicas) + len(state.starting)
            if actual < target and time.monotonic() >= state.backoff_until:
                for _ in range(target - actual):
                    self._start_replica(state)
            elif actual > target:
                excess = actual - target
                # Prefer stopping not-yet-ready replicas.
                ordered = sorted(state.replicas.values(), key=lambda r: r.ready)
                for rec in ordered[:excess]:
                    self._start_stopping(state, rec)
            self._update_deployment_status(state)
            if state.deleting and not (
                state.replicas or state.starting or state.stopping
            ):
                del self._deployments[key]
                self._broadcast_replicas(key)
        self._update_app_statuses()

    def _update_deployment_status(self, state: _DeploymentState) -> None:
        if state.deleting:
            state.status = DeploymentStatus.DELETING
            return
        ready = sum(1 for r in state.replicas.values() if r.ready)
        if ready == state.target_replicas and not state.starting:
            state.status = DeploymentStatus.HEALTHY
        elif state.status != DeploymentStatus.UNHEALTHY:
            state.status = (
                DeploymentStatus.UPSCALING
                if ready < state.target_replicas
                else DeploymentStatus.DOWNSCALING
            )

    def _update_app_statuses(self) -> None:
        for app_name, app in self._apps.items():
            if app["status"] == ApplicationStatus.DELETING:
                if not any(
                    str(DeploymentID(d, app_name)) in self._deployments
                    for d in app["deployments"]
                ):
                    del self._apps[app_name]
                    self._broadcast_routes()
                    return
                continue
            statuses = []
            for d in app["deployments"]:
                state = self._deployments.get(str(DeploymentID(d, app_name)))
                if state is not None:
                    statuses.append(state.status)
            if any(s == DeploymentStatus.UNHEALTHY for s in statuses):
                new = ApplicationStatus.DEPLOY_FAILED
            elif statuses and all(s == DeploymentStatus.HEALTHY for s in statuses):
                new = ApplicationStatus.RUNNING
            else:
                new = ApplicationStatus.DEPLOYING
            if new != app["status"]:
                app["status"] = new
                self._broadcast_routes()

    # -- replica lifecycle ---------------------------------------------------

    def _start_replica(self, state: _DeploymentState) -> None:
        replica_id = ReplicaID.generate(state.dep_id)
        task = _spawn(self._create_replica(state, replica_id))
        state.starting[replica_id.unique_id] = task

    async def _create_replica(
        self, state: _DeploymentState, replica_id: ReplicaID
    ) -> None:
        from ray_tpu.serve._private.replica import Replica

        core = worker_mod._core()
        cfg = state.config
        actor_id = None
        try:
            opts = dict(cfg.ray_actor_options)
            resources = {"CPU": float(opts.get("num_cpus", 0.1))}
            if opts.get("num_tpus"):
                resources["TPU"] = float(opts["num_tpus"])
            for k, v in (opts.get("resources") or {}).items():
                resources[k] = float(v)
            init_args, init_kwargs = cloudpickle.loads(state.spec["init_args_blob"])
            actor_id = await core.create_actor(
                cloudpickle.dumps(Replica),
                f"ServeReplica:{state.dep_id.app_name}:{state.dep_id.name}",
                (
                    state.spec["serialized_cls"],
                    init_args,
                    init_kwargs,
                    str(state.dep_id),
                    replica_id.unique_id,
                    cfg.user_config,
                    cfg.max_batch_size,
                    cfg.batch_wait_timeout_s,
                    cfg.max_ongoing_requests,
                ),
                {},
                resources=resources,
                max_concurrency=max(cfg.max_ongoing_requests, 8),
                name=replica_id.to_actor_name(),
                namespace="serve",
                lifetime="detached",
            )
            # Readiness ping (also surfaces user __init__ errors).
            refs = await core.submit_actor_task(
                actor_id, "check_health", (), {}, num_returns=1
            )
            await asyncio.wait_for(
                core.get_objects(refs[0], timeout=None),
                timeout=cfg.health_check_timeout_s,
            )
            rec = _ReplicaRecord(
                replica_id,
                actor_id,
                cfg.max_ongoing_requests,
                cfg.max_queued_requests,
            )
            rec.ready = True
            state.replicas[replica_id.unique_id] = rec
            rec.health_task = _spawn(self._health_loop(state, rec))
            self._arm_death_watch(state, rec)
            state.message = ""
            state.consecutive_start_failures = 0
            state.backoff_until = 0.0
            self._broadcast_replicas(str(state.dep_id))
        except Exception as e:
            state.status = DeploymentStatus.UNHEALTHY
            state.message = f"replica start failed: {type(e).__name__}: {e}"
            state.consecutive_start_failures += 1
            state.backoff_until = time.monotonic() + min(
                30.0, 0.5 * 2**state.consecutive_start_failures
            )
            if actor_id is not None:
                # Don't leak the half-started detached actor.
                try:
                    await core.kill_actor(actor_id)
                except Exception:
                    pass
            logger.warning(
                "replica %s of %s failed to start: %s",
                replica_id.unique_id,
                state.dep_id,
                state.message,
            )
        finally:
            state.starting.pop(replica_id.unique_id, None)

    async def _reconfigure_replicas(
        self, state: _DeploymentState, user_config: Any
    ) -> None:
        core = worker_mod._core()
        for rec in list(state.replicas.values()):
            try:
                refs = await core.submit_actor_task(
                    rec.actor_id, "reconfigure", (user_config,), {}, num_returns=1
                )
                await asyncio.wait_for(
                    core.get_objects(refs[0], timeout=None),
                    _config.serve_reconfigure_timeout_s,
                )
            except Exception as e:
                logger.warning(
                    "reconfigure of replica %s failed: %r; replacing",
                    rec.replica_id.unique_id,
                    e,
                )
                self._start_stopping(state, rec)

    async def _health_loop(self, state: _DeploymentState, rec: _ReplicaRecord) -> None:
        """Periodic replica health check (reference deployment_state.py
        check_health path): 3 consecutive failures -> replace the replica."""
        core = worker_mod._core()
        period = state.config.health_check_period_s
        while rec.replica_id.unique_id in state.replicas and not self._shutdown:
            await asyncio.sleep(period)
            try:
                refs = await core.submit_actor_task(
                    rec.actor_id, "check_health", (), {}, num_returns=1
                )
                await asyncio.wait_for(
                    core.get_objects(refs[0], timeout=None),
                    timeout=state.config.health_check_timeout_s,
                )
                rec.consecutive_health_failures = 0
            except asyncio.CancelledError:
                return
            except Exception:
                rec.consecutive_health_failures += 1
                if rec.consecutive_health_failures >= 3:
                    if rec.replica_id.unique_id in state.replicas:
                        logger.warning(
                            "replica %s of %s failed health checks; replacing",
                            rec.replica_id.unique_id,
                            state.dep_id,
                        )
                        self._start_stopping(state, rec)
                    return

    def _arm_death_watch(self, state: _DeploymentState, rec: _ReplicaRecord) -> None:
        """Replace a replica the moment the GCS declares its actor DEAD.

        The RPC health loop needs up to ``health_check_timeout_s`` plus two
        more periods to call a SIGKILLed replica dead — seconds in which
        routers still list the corpse. The GCS hears about the worker's
        death from its raylet almost immediately and publishes the actor
        state transition, so subscribing here turns replacement latency
        from seconds into one reconcile tick."""
        core = worker_mod._core()

        def on_update(msg) -> None:
            if (msg or {}).get("state") != ACTOR_DEAD:
                return
            if (
                self._shutdown
                or state.replicas.get(rec.replica_id.unique_id) is not rec
            ):
                return
            logger.warning(
                "replica %s of %s actor died (%s); replacing",
                rec.replica_id.unique_id,
                state.dep_id,
                (msg or {}).get("death_cause") or "no cause recorded",
            )
            self._start_stopping(state, rec)

        rec.death_watch = on_update

        # snapshot=True closes the subscribe-after-publish race (the actor
        # may have died before the Subscribe landed and that publish is
        # gone): the GcsClient delivers the current actor state to this
        # handler right after subscribing, and the same snapshot pull
        # re-fires automatically whenever a pubsub seq gap is detected.
        _spawn(
            core.gcs.subscribe(
                f"actor:{rec.actor_id}", on_update, snapshot=True
            )
        )

    def _start_stopping(self, state: _DeploymentState, rec: _ReplicaRecord) -> None:
        if rec.health_task is not None:
            rec.health_task.cancel()
            rec.health_task = None
        if rec.death_watch is not None:
            handler, rec.death_watch = rec.death_watch, None
            _spawn(
                worker_mod._core().gcs.unsubscribe(
                    f"actor:{rec.actor_id}", handler
                )
            )
        state.replicas.pop(rec.replica_id.unique_id, None)
        self._broadcast_replicas(str(state.dep_id))
        task = _spawn(self._stop_replica(state, rec))
        state.stopping[rec.replica_id.unique_id] = task

    async def _stop_replica(self, state: _DeploymentState, rec: _ReplicaRecord) -> None:
        core = worker_mod._core()
        try:
            refs = await core.submit_actor_task(
                rec.actor_id,
                "prepare_for_shutdown",
                (state.config.graceful_shutdown_timeout_s,),
                {},
                num_returns=1,
            )
            await asyncio.wait_for(
                core.get_objects(refs[0], timeout=None),
                timeout=state.config.graceful_shutdown_timeout_s
                + _config.serve_shutdown_grace_s,
            )
        except Exception:
            pass
        try:
            await core.kill_actor(rec.actor_id)
        except Exception:
            pass
        state.stopping.pop(rec.replica_id.unique_id, None)

    # -- autoscaling ---------------------------------------------------------

    def _router_queue_depth(
        self, dep_key: str, ac: AutoscalingConfig, now: float
    ) -> int:
        """Sum queued requests across routers, ignoring (and pruning) entries
        older than queue_metric_staleness_s — a dead router must not pin the
        depth at its last reported value forever."""
        total = 0
        for (key, router_id), (ts, queued, _ongoing) in list(
            self._router_metrics.items()
        ):
            if now - ts > ac.queue_metric_staleness_s:
                del self._router_metrics[(key, router_id)]
                continue
            if key == dep_key:
                total += queued
        return total

    def _autoscale(self, state: _DeploymentState) -> None:
        ac = state.config.autoscaling_config
        if ac is None or state.deleting:
            return
        now = time.monotonic()
        # Sample metrics (fire-and-forget gather; cheap at control-loop rate).
        _spawn(self._sample_metrics(state, now, ac))
        depth = self._router_queue_depth(str(state.dep_id), ac, now)
        alpha = ac.queue_ewma_alpha
        state.queue_ewma = alpha * depth + (1.0 - alpha) * state.queue_ewma
        new_target = autoscale_tick(state, ac, now)
        if new_target is not None and new_target != state.target_replicas:
            logger.info(
                "autoscaling %s: %d -> %d (queue_ewma=%.1f)",
                state.dep_id,
                state.target_replicas,
                new_target,
                state.queue_ewma,
            )
            direction = "up" if new_target > state.target_replicas else "down"
            _TEL_AUTOSCALE.cell(direction=direction).inc()
            telemetry.record_event(
                "serve", "autoscale", deployment=str(state.dep_id),
                direction=direction, old=state.target_replicas,
                new=new_target,
            )
            state.current_target = new_target

    async def _sample_metrics(
        self, state: _DeploymentState, ts: float, ac: AutoscalingConfig
    ) -> None:
        core = worker_mod._core()
        total = 0
        for rec in list(state.replicas.values()):
            if not rec.ready:
                continue
            try:
                refs = await core.submit_actor_task(
                    rec.actor_id, "get_metrics", (), {}, num_returns=1
                )
                m = await asyncio.wait_for(
                    core.get_objects(refs[0], timeout=None),
                    timeout=_config.serve_metrics_sample_timeout_s,
                )
                total += m.get("num_ongoing_requests", 0)
                rec.consecutive_health_failures = 0
            except Exception:
                rec.consecutive_health_failures += 1
                if rec.consecutive_health_failures >= 3:
                    logger.warning(
                        "replica %s unhealthy; replacing", rec.replica_id.unique_id
                    )
                    self._start_stopping(state, rec)
        state.metrics_window.append((ts, total))
