"""DeploymentHandle: call a deployment from Python (driver or other replicas).

Analog of python/ray/serve/handle.py: `handle.remote(*args)` returns a
DeploymentResponse — sync callers use `.result()`, async callers `await` it.
Handles serialize as (app, deployment) names, so they can be passed as init
args to downstream deployments for model composition.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Dict, Optional, Tuple

from ray_tpu._private import worker as worker_mod
from ray_tpu.serve._private.common import DeploymentID, RequestMetadata

_router_lock = threading.Lock()
_process_router = None  # one Router per process, shared across handles


async def _get_router():
    """Lazily build the process-wide Router. Always runs on the runtime event
    loop, so the controller lookup uses the async GCS path (a sync lookup here
    would deadlock when called from inside a replica)."""
    global _process_router
    if _process_router is None:
        from ray_tpu.actor import ActorHandle
        from ray_tpu.serve._private.common import CONTROLLER_NAME, SERVE_NAMESPACE
        from ray_tpu.serve._private.router import Router

        core = worker_mod._core()
        reply = await core.gcs.call(
            "GetNamedActor", {"name": CONTROLLER_NAME, "namespace": SERVE_NAMESPACE}
        )
        info = reply["actor"]
        if info is None or info["state"] == "DEAD":
            raise RuntimeError("Serve is not running (no controller actor)")
        _process_router = Router(ActorHandle(info["actor_id"]), core)
    return _process_router


def _reset_router() -> None:
    global _process_router
    with _router_lock:
        if _process_router is not None:
            _process_router.shutdown()
        _process_router = None


class DeploymentResponse:
    """Future-like result of handle.remote() (reference handle.py
    DeploymentResponse). Awaitable, and `.result(timeout_s)` for sync code."""

    def __init__(self, cf):
        self._cf = cf  # concurrent.futures.Future from run_coroutine_threadsafe

    def result(self, timeout_s: Optional[float] = None) -> Any:
        w = worker_mod.global_worker
        if threading.current_thread() is w._loop_thread:
            raise RuntimeError(
                "DeploymentResponse.result() called on the event loop; "
                "use `await response` in async code"
            )
        return self._cf.result(timeout_s)

    def __await__(self):
        return asyncio.wrap_future(self._cf).__await__()

    def cancel(self) -> None:
        self._cf.cancel()


class DeploymentHandle:
    def __init__(
        self,
        deployment_name: str,
        app_name: str = "default",
        *,
        method_name: str = "__call__",
        multiplexed_model_id: str = "",
    ):
        self.deployment_id = DeploymentID(deployment_name, app_name)
        self._method_name = method_name
        self._multiplexed_model_id = multiplexed_model_id

    def options(
        self,
        *,
        method_name: Optional[str] = None,
        multiplexed_model_id: Optional[str] = None,
    ) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment_id.name,
            self.deployment_id.app_name,
            method_name=method_name or self._method_name,
            multiplexed_model_id=(
                multiplexed_model_id
                if multiplexed_model_id is not None
                else self._multiplexed_model_id
            ),
        )

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        w = worker_mod.global_worker
        meta = RequestMetadata(call_method=self._method_name)

        async def _assign():
            router = await _get_router()
            # Model composition: resolve nested responses/handles in args.
            rargs = []
            for a in args:
                if isinstance(a, DeploymentResponse):
                    a = await a
                rargs.append(a)
            rkwargs = {}
            for k, v in kwargs.items():
                if isinstance(v, DeploymentResponse):
                    v = await v
                rkwargs[k] = v
            return await router.assign_request(
                str(self.deployment_id),
                {
                    "call_method": meta.call_method,
                    "request_id": meta.request_id,
                    "multiplexed_model_id": self._multiplexed_model_id,
                },
                tuple(rargs),
                rkwargs,
            )

        cf = asyncio.run_coroutine_threadsafe(_assign(), w.loop)
        return DeploymentResponse(cf)

    def __reduce__(self):
        return (
            _rebuild_handle,
            (
                self.deployment_id.name,
                self.deployment_id.app_name,
                self._method_name,
                self._multiplexed_model_id,
            ),
        )

    def __repr__(self):
        return f"DeploymentHandle({self.deployment_id})"


def _rebuild_handle(
    name: str, app_name: str, method_name: str, multiplexed_model_id: str = ""
) -> DeploymentHandle:
    return DeploymentHandle(
        name,
        app_name,
        method_name=method_name,
        multiplexed_model_id=multiplexed_model_id,
    )
