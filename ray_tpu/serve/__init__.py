"""ray_tpu.serve: online model serving on the ray_tpu runtime.

Same capability surface as the reference's Ray Serve (python/ray/serve):
deployments with replica autoscaling, an HTTP proxy with pow-2 routing, model
composition via deployment handles, and a reconciling controller actor.

    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    class Model:
        def __call__(self, x):
            return x * 2

    handle = serve.run(Model.bind())
    assert handle.remote(2).result() == 4
"""

from ray_tpu.serve._private.common import DeploymentOverloadedError
from ray_tpu.serve._private.proxy import HTTPRequest
from ray_tpu.serve.api import (
    Application,
    Deployment,
    delete,
    deployment,
    get_app_handle,
    get_deployment_handle,
    get_multiplexed_model_id,
    ingress,
    multiplexed,
    run,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse
from ray_tpu.serve.schema import AutoscalingConfig, DeploymentConfig, HTTPOptions

__all__ = [
    "Application",
    "AutoscalingConfig",
    "Deployment",
    "DeploymentConfig",
    "DeploymentHandle",
    "DeploymentOverloadedError",
    "DeploymentResponse",
    "HTTPOptions",
    "HTTPRequest",
    "delete",
    "deployment",
    "get_app_handle",
    "get_deployment_handle",
    "get_multiplexed_model_id",
    "ingress",
    "multiplexed",
    "run",
    "shutdown",
    "start",
    "status",
]
