"""Serve public API: @serve.deployment, serve.run, serve.status, ...

Analog of python/ray/serve/api.py (serve.run:545, @serve.deployment:248).
`Deployment.bind(*args)` builds an application graph (args may be other bound
deployments — model composition); `serve.run` deploys it through the
controller and returns a handle to the ingress deployment.
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple, Union

import cloudpickle

import ray_tpu
from ray_tpu.serve._private.common import (
    CONTROLLER_NAME,
    DEFAULT_APP_NAME,
    SERVE_NAMESPACE,
)
from ray_tpu.serve.handle import DeploymentHandle, _reset_router
from ray_tpu.serve.schema import AutoscalingConfig, DeploymentConfig, HTTPOptions

_controller_handle = None

# Per-class no-op-__del__ subclasses used by @multiplexed eviction; cached so
# repeated evictions of the same model class reuse one type object.
_neutered_classes: Dict[type, type] = {}


@dataclass
class Application:
    """A bound deployment DAG node (reference: serve.built_application /
    Application). `args` may contain other Application nodes."""

    deployment: "Deployment"
    args: Tuple = ()
    kwargs: Dict[str, Any] = field(default_factory=dict)


class Deployment:
    def __init__(self, func_or_class, name: str, config: DeploymentConfig):
        self._func_or_class = func_or_class
        self.name = name
        self.config = config

    def options(self, **kwargs) -> "Deployment":
        cfg = DeploymentConfig.from_dict(self.config.to_dict())
        name = kwargs.pop("name", self.name)
        if "autoscaling_config" in kwargs:
            ac = kwargs.pop("autoscaling_config")
            cfg.autoscaling_config = (
                AutoscalingConfig.from_dict(ac) if isinstance(ac, dict) else ac
            )
        for k, v in kwargs.items():
            if not hasattr(cfg, k):
                raise TypeError(f"unknown deployment option {k!r}")
            setattr(cfg, k, v)
        return Deployment(self._func_or_class, name, cfg)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def __call__(self, *a, **k):
        raise TypeError(
            f"deployment {self.name!r} cannot be called directly; use "
            "serve.run(deployment.bind(...)) and call the returned handle"
        )


def deployment(
    _func_or_class=None,
    *,
    name: Optional[str] = None,
    num_replicas: Union[int, str, None] = None,
    max_ongoing_requests: Optional[int] = None,
    max_queued_requests: Optional[int] = None,
    max_batch_size: Optional[int] = None,
    batch_wait_timeout_s: Optional[float] = None,
    autoscaling_config: Union[AutoscalingConfig, dict, None] = None,
    user_config: Optional[Any] = None,
    health_check_period_s: Optional[float] = None,
    health_check_timeout_s: Optional[float] = None,
    graceful_shutdown_timeout_s: Optional[float] = None,
    ray_actor_options: Optional[Dict[str, Any]] = None,
):
    """Decorator converting a class (or function) into a Deployment."""

    def build(obj) -> Deployment:
        cfg = DeploymentConfig()
        if num_replicas is not None and num_replicas != "auto":
            cfg.num_replicas = int(num_replicas)
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if max_queued_requests is not None:
            cfg.max_queued_requests = max_queued_requests
        if max_batch_size is not None:
            cfg.max_batch_size = max_batch_size
        if batch_wait_timeout_s is not None:
            cfg.batch_wait_timeout_s = batch_wait_timeout_s
        ac = autoscaling_config
        if num_replicas == "auto" and ac is None:
            ac = AutoscalingConfig(min_replicas=1, max_replicas=8)
        if ac is not None:
            cfg.autoscaling_config = (
                AutoscalingConfig.from_dict(ac) if isinstance(ac, dict) else ac
            )
        if user_config is not None:
            cfg.user_config = user_config
        if health_check_period_s is not None:
            cfg.health_check_period_s = health_check_period_s
        if health_check_timeout_s is not None:
            cfg.health_check_timeout_s = health_check_timeout_s
        if graceful_shutdown_timeout_s is not None:
            cfg.graceful_shutdown_timeout_s = graceful_shutdown_timeout_s
        if ray_actor_options is not None:
            cfg.ray_actor_options = dict(ray_actor_options)
        return Deployment(obj, name or obj.__name__, cfg)

    if _func_or_class is not None:
        return build(_func_or_class)
    return build


def ingress(_cls=None):
    """No-op marker for API parity with the reference's FastAPI ingress."""
    return _cls if _cls is not None else (lambda c: c)


# -- controller management ----------------------------------------------------


def _get_controller():
    global _controller_handle
    if _controller_handle is not None:
        return _controller_handle
    _controller_handle = ray_tpu.get_actor(CONTROLLER_NAME, namespace=SERVE_NAMESPACE)
    return _controller_handle


def start(http_options: Union[HTTPOptions, dict, None] = None, **kwargs):
    """Ensure the Serve controller (and proxy) is running."""
    global _controller_handle
    if http_options is None:
        http_options = HTTPOptions(**kwargs) if kwargs else HTTPOptions(port=0)
    elif isinstance(http_options, dict):
        http_options = HTTPOptions(**http_options)
    try:
        handle = _get_controller()
    except ValueError:
        from ray_tpu.serve._private.controller import ServeController

        handle = (
            ray_tpu.remote(ServeController)
            .options(
                name=CONTROLLER_NAME,
                namespace=SERVE_NAMESPACE,
                lifetime="detached",
                max_concurrency=1000,
                num_cpus=0.1,
                get_if_exists=True,
            )
            .remote(http_options.to_dict())
        )
        _controller_handle = handle
    ray_tpu.get(handle.start.remote())
    return handle


def _collect_deployments(
    app: Application, out: Dict[str, Tuple[Deployment, Tuple, Dict]], app_name: str
) -> str:
    """DFS over the bind graph; nested Applications become handles."""
    dep = app.deployment

    def resolve(v):
        if isinstance(v, Application):
            child = _collect_deployments(v, out, app_name)
            return DeploymentHandle(child, app_name)
        return v

    args = tuple(resolve(a) for a in app.args)
    kwargs = {k: resolve(v) for k, v in app.kwargs.items()}
    if dep.name in out and out[dep.name][0] is not dep:
        raise ValueError(f"duplicate deployment name {dep.name!r} in application")
    out[dep.name] = (dep, args, kwargs)
    return dep.name


def run(
    target: Application,
    *,
    name: str = DEFAULT_APP_NAME,
    route_prefix: Optional[str] = "/",
    blocking: bool = False,
    _timeout_s: float = 60.0,
) -> DeploymentHandle:
    """Deploy an application and wait until it is RUNNING."""
    if isinstance(target, Deployment):
        target = target.bind()
    if not isinstance(target, Application):
        raise TypeError("serve.run expects an Application (deployment.bind(...))")
    controller = start()

    deployments: Dict[str, Tuple[Deployment, Tuple, Dict]] = {}
    ingress_name = _collect_deployments(target, deployments, name)

    dep_specs = []
    for dep_name, (dep, args, kwargs) in deployments.items():
        serialized_cls = cloudpickle.dumps(dep._func_or_class)
        init_blob = cloudpickle.dumps((args, kwargs))
        version = hashlib.sha1(serialized_cls + init_blob).hexdigest()[:16]
        dep_specs.append(
            {
                "name": dep_name,
                "serialized_cls": serialized_cls,
                "init_args_blob": init_blob,
                "config": dep.config.to_dict(),
                "version": version,
            }
        )
    app_spec = {
        "name": name,
        "route_prefix": route_prefix,
        "ingress": ingress_name,
        "deployments": dep_specs,
    }
    ray_tpu.get(controller.deploy_application.remote(app_spec))

    deadline = time.monotonic() + _timeout_s
    while True:
        statuses = ray_tpu.get(controller.get_serve_status.remote())
        info = statuses.get(name, {})
        if info.get("status") == "RUNNING":
            break
        if info.get("status") == "DEPLOY_FAILED":
            msgs = {
                d: s.get("message")
                for d, s in info.get("deployments", {}).items()
                if s.get("message")
            }
            raise RuntimeError(f"deploying app {name!r} failed: {msgs}")
        if time.monotonic() > deadline:
            raise TimeoutError(f"app {name!r} not RUNNING after {_timeout_s}s: {info}")
        time.sleep(0.1)

    handle = DeploymentHandle(ingress_name, name)
    if blocking:
        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            pass
    return handle


def delete(name: str, _blocking: bool = True) -> None:
    controller = _get_controller()
    ray_tpu.get(controller.delete_application.remote(name))
    if _blocking:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if name not in ray_tpu.get(controller.get_serve_status.remote()):
                return
            time.sleep(0.1)


def status() -> Dict[str, Any]:
    try:
        controller = _get_controller()
    except ValueError:
        return {}
    return ray_tpu.get(controller.get_serve_status.remote())


def get_deployment_handle(
    deployment_name: str, app_name: str = DEFAULT_APP_NAME
) -> DeploymentHandle:
    return DeploymentHandle(deployment_name, app_name)


def get_app_handle(name: str = DEFAULT_APP_NAME) -> DeploymentHandle:
    info = status().get(name)
    if info is None:
        raise ValueError(f"no application named {name!r}")
    ing = info.get("ingress")
    if not ing:
        deps = list(info.get("deployments", {}))
        if len(deps) != 1:
            raise ValueError(f"cannot determine ingress of app {name!r}")
        ing = deps[0]
    return DeploymentHandle(ing, name)


# -- model multiplexing (reference: serve/api.py @serve.multiplexed +
# get_multiplexed_model_id) ---------------------------------------------------

import contextvars as _contextvars

_multiplexed_model_id_ctx: "_contextvars.ContextVar[str]" = _contextvars.ContextVar(
    "serve_multiplexed_model_id", default=""
)


def get_multiplexed_model_id() -> str:
    """Inside a replica: the model id of the in-flight multiplexed request
    (from the gRPC/HTTP ``multiplexed_model_id`` metadata)."""
    return _multiplexed_model_id_ctx.get()


def multiplexed(func=None, *, max_num_models_per_replica: int = 3):
    """Decorate an async per-model loader on a deployment: loads are cached
    per model id with LRU eviction at ``max_num_models_per_replica``; the
    router keeps a model's requests sticky to the replica that loaded it."""
    import asyncio as _asyncio
    import collections as _collections
    import functools as _functools

    def deco(fn):
        cache: "_collections.OrderedDict[str, Any]" = _collections.OrderedDict()
        locks: Dict[str, Any] = {}

        @_functools.wraps(fn)
        async def wrapper(self_or_id, model_id=None):
            # Supports both bound-method (self, model_id) and free (model_id).
            if model_id is None:
                target_id = self_or_id
                call = lambda: fn(target_id)  # noqa: E731
            else:
                target_id = model_id
                call = lambda: fn(self_or_id, target_id)  # noqa: E731
            if target_id in cache:
                cache.move_to_end(target_id)
                return cache[target_id]
            lock = locks.setdefault(target_id, _asyncio.Lock())
            async with lock:
                if target_id in cache:
                    cache.move_to_end(target_id)
                    return cache[target_id]
                model = call()
                if _asyncio.iscoroutine(model):
                    model = await model
                cache[target_id] = model
                while len(cache) > max_num_models_per_replica:
                    evicted_id, evicted = cache.popitem(last=False)
                    locks.pop(evicted_id, None)
                    # Release eagerly (reference evicts with explicit
                    # deletion so TPU/GPU memory frees before the next load).
                    del_fn = getattr(evicted, "__del__", None)
                    if del_fn is not None:
                        try:
                            del_fn()
                        except Exception:
                            pass
                        # Neutralize so GC doesn't run the destructor a
                        # second time (double resource release — reference:
                        # serve/multiplex.py:245-252 replaces __del__ after
                        # the explicit call; it uses an instance setattr,
                        # which CPython ignores for dunders, so swap in a
                        # per-instance subclass with a no-op __del__).
                        try:
                            cls = type(evicted)
                            neutered = _neutered_classes.get(cls)
                            if neutered is None:
                                neutered = type(
                                    cls.__name__,
                                    (cls,),
                                    {"__del__": lambda _s: None,
                                     "__qualname__": cls.__qualname__,
                                     "__module__": cls.__module__},
                                )
                                _neutered_classes[cls] = neutered
                            evicted.__class__ = neutered
                        except Exception:
                            # __slots__/extension types (TypeError) or a
                            # model class's __init_subclass__ hook rejecting
                            # the subclass: accept the destructor rerun.
                            pass
                return model

        return wrapper

    if func is not None:
        return deco(func)
    return deco


def shutdown() -> None:
    """Tear down all Serve actors."""
    global _controller_handle
    try:
        controller = _get_controller()
    except Exception:
        _controller_handle = None
        _reset_router()
        return
    try:
        ray_tpu.get(controller.graceful_shutdown.remote(), timeout=30)
    except Exception:
        pass
    try:
        ray_tpu.kill(controller)
    except Exception:
        pass
    _controller_handle = None
    _reset_router()
