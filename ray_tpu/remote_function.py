"""@ray_tpu.remote functions.

Analog of python/ray/remote_function.py: RemoteFunction wraps the user function,
pickles it once, and `_remote` submits through the core worker.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu._private import worker as worker_mod


def _build_resources(opts: Dict[str, Any]) -> Dict[str, float]:
    resources = dict(opts.get("resources") or {})
    if opts.get("num_cpus") is not None:
        resources["CPU"] = float(opts["num_cpus"])
    elif "CPU" not in resources:
        resources["CPU"] = 1.0
    if opts.get("num_tpus") is not None:
        resources["TPU"] = float(opts["num_tpus"])
    if opts.get("memory") is not None:
        resources["memory"] = float(opts["memory"])
    return resources


def _strategy_fields(opts):
    """Extract (pg_id, bundle_index, strategy_dict) from scheduling options."""
    pg_id, bundle_index, strategy = None, -1, None
    ss = opts.get("scheduling_strategy")
    if ss == "SPREAD":
        # Reference: scheduling_strategy="SPREAD" places tasks on the
        # least-loaded feasible nodes (scheduling_options.h SPREAD).
        return None, -1, {"spread": True}
    if ss == "DEFAULT":
        return None, -1, None
    if ss is not None:
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
            NodeLabelSchedulingStrategy,
            PlacementGroupSchedulingStrategy,
        )

        if isinstance(ss, PlacementGroupSchedulingStrategy):
            pg_id = ss.placement_group.id_hex
            bundle_index = ss.placement_group_bundle_index
        elif isinstance(ss, NodeAffinitySchedulingStrategy):
            strategy = {"node_id": ss.node_id, "soft": ss.soft}
        elif isinstance(ss, NodeLabelSchedulingStrategy):
            strategy = ss.to_wire()
        elif isinstance(ss, dict):
            strategy = ss
    if opts.get("placement_group") is not None:
        pg_id = opts["placement_group"].id_hex
        bundle_index = opts.get("placement_group_bundle_index", -1)
    return pg_id, bundle_index, strategy


class RemoteFunction:
    def __init__(self, fn, **options):
        import asyncio

        self._fn = fn
        self._options = options
        self._pickled: Optional[bytes] = None
        # Per-call-invariant submission fields, computed once (the resource
        # fixed-point conversion and strategy unpacking are hot-path costs).
        self._res_units: Optional[Dict[str, int]] = None
        self._strategy_cache = None
        # Coroutine functions need the worker's event loop — permanently
        # ineligible for the native fastpath (gating here avoids a
        # per-call status-4 bounce off the worker).
        self._no_fastpath = asyncio.iscoroutinefunction(fn)
        functools.update_wrapper(self, fn)

    def _get_pickled(self) -> bytes:
        if self._pickled is None:
            self._pickled = cloudpickle.dumps(self._fn)
        return self._pickled

    def options(self, **options) -> "RemoteFunction":
        merged = {**self._options, **options}
        clone = RemoteFunction(self._fn, **merged)
        clone._pickled = self._pickled
        return clone

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs)

    def bind(self, *args, **kwargs):
        """Lazy task node for workflows (ray_tpu.workflow.run(fn.bind(...)))."""
        from ray_tpu.workflow.api import FunctionNode

        return FunctionNode(self, args, kwargs)

    def _remote(self, args, kwargs):
        opts = self._options
        w = worker_mod.global_worker
        if w.mode == "client":
            refs = w.client.submit_remote_function(self, args, kwargs)
            num_returns = opts.get("num_returns", 1)
            return refs[0] if num_returns in (1, "dynamic") else refs
        core = worker_mod._core()
        if self._strategy_cache is None:
            self._strategy_cache = _strategy_fields(opts)
        pg_id, bundle_index, strategy = self._strategy_cache
        if self._res_units is None:
            from ray_tpu._private.common import ResourceSet

            self._res_units = ResourceSet(_build_resources(opts)).to_units()
        name = opts.get("name") or getattr(self._fn, "__name__", "task")
        # Thread-side fast path: skips the run_coroutine_threadsafe round trip
        # (the dominant cost of .remote()); falls back for first-call export,
        # runtime envs, and plasma-sized args.
        refs = core.try_submit_task_fast(
            self._get_pickled(),
            name,
            args,
            kwargs,
            loop=worker_mod.global_worker.loop,
            num_returns=opts.get("num_returns", 1),
            resources_units=self._res_units,
            max_retries=opts.get("max_retries"),
            retry_exceptions=opts.get("retry_exceptions", False),
            pg_id=pg_id,
            bundle_index=bundle_index,
            scheduling_strategy=strategy,
            runtime_env=opts.get("runtime_env"),
            no_fastpath=self._no_fastpath,
        )
        if refs is None:
            refs = worker_mod.global_worker.run_async(
                core.submit_task(
                    self._get_pickled(),
                    name,
                    args,
                    kwargs,
                    num_returns=opts.get("num_returns", 1),
                    resources=_build_resources(opts),
                    max_retries=opts.get("max_retries"),
                    retry_exceptions=opts.get("retry_exceptions", False),
                    pg_id=pg_id,
                    bundle_index=bundle_index,
                    scheduling_strategy=strategy,
                    runtime_env=opts.get("runtime_env"),
                )
            )
        num_returns = opts.get("num_returns", 1)
        if num_returns == 1 or num_returns == "dynamic":
            return refs[0]
        return refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self.__name__!r} cannot be called directly; "
            "use .remote()"
        )
