"""Shared AIR-style configuration layer (reference: python/ray/air).

Holds the config dataclasses used by both train and tune:
ScalingConfig / RunConfig / FailureConfig / CheckpointConfig
(reference: python/ray/air/config.py) and the terminal Result object.
"""

from ray_tpu.air.config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)

__all__ = [
    "ScalingConfig",
    "RunConfig",
    "FailureConfig",
    "CheckpointConfig",
    "Result",
]
