"""Run/scaling/failure/checkpoint configs (reference: python/ray/air/config.py).

TPU-first deviation: ScalingConfig thinks in *hosts* — one train worker per
TPU host (multi-controller JAX), each owning all local chips, with intra-host
parallelism expressed as mesh axes rather than extra workers. `use_tpu` plays
the role the reference's `use_gpu` does.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class ScalingConfig:
    """How many train workers and what each reserves.

    reference: python/ray/air/config.py ScalingConfig (num_workers/use_gpu/
    resources_per_worker/placement_strategy).
    """

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    # TPU-native extras: chips each worker (host) owns, and the topology
    # (e.g. "v5e-64") used to pick the per-pod gang resource.
    tpu_chips_per_worker: int = 0
    topology: Optional[str] = None

    def _worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        if "CPU" not in res:
            res["CPU"] = 1.0
        if self.use_tpu and "TPU" not in res:
            res["TPU"] = float(self.tpu_chips_per_worker or 1)
        return res

    def as_placement_group_bundles(self) -> List[Dict[str, float]]:
        return [self._worker_resources() for _ in range(self.num_workers)]

    @property
    def total_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for b in self.as_placement_group_bundles():
            for k, v in b.items():
                out[k] = out.get(k, 0.0) + v
        return out


@dataclass
class FailureConfig:
    """Retries for whole training runs (reference: air/config.py FailureConfig).

    On TPU a slice is all-or-nothing: any worker death tears down the gang, so
    retry = re-gang the whole worker group and resume from the latest
    checkpoint (SURVEY.md §7 'Gang semantics') — not per-worker restart.
    """

    max_failures: int = 0


@dataclass
class CheckpointConfig:
    """Top-K retention (reference: air/config.py CheckpointConfig)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")
        if self.num_to_keep is not None and self.num_to_keep <= 0:
            raise ValueError("num_to_keep must be positive or None")


@dataclass
class RunConfig:
    """Experiment-level settings (reference: air/config.py RunConfig)."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 1
    # Stop criteria for trials: {"metric": threshold} — a trial stops once
    # any listed metric reaches its threshold (reference: the `stop` dict of
    # tune.RunConfig; how class Trainables are bounded).
    stop: Optional[Dict[str, Any]] = None

    def resolved_storage_path(self) -> str:
        return self.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results"
        )


@dataclass
class Result:
    """Terminal state of a run/trial (reference: air/result.py Result)."""

    metrics: Optional[Dict[str, Any]]
    checkpoint: Optional[Any]  # train.Checkpoint
    path: Optional[str]
    error: Optional[BaseException] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def best_checkpoints(self):
        return getattr(self, "_best_checkpoints", [])
