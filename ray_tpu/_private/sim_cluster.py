"""Simulated-cluster harness: hundreds of in-process raylets on loopback.

The scale tests, the scheduler benchmark (``ray_perf._bench_sched``) and
the chaos ``sched`` scenario all need a cluster that is *real* at the
control plane — every raylet runs the actual lease scheduler, spillback
protocol and delta-synced cluster view over real loopback RPC — but fake
at the worker plane, because forking 4000 worker subprocesses to study
scheduling at 1000 nodes would measure the OS, not the scheduler. The
harness pairs three pieces:

- ``SimCluster`` boots a real ``GcsServer`` plus N real ``Raylet``
  instances with ``sim_workers=True`` (grants attach in-process stub
  workers, see raylet.py ``_make_sim_worker``) on a dedicated event-loop
  thread, so synchronous tests drive it with ``run()``.
- ``SimLeaseClient`` speaks the lease protocol the way ``core_worker``
  does — spillback chains with ``spilled_from`` pinning, the hop-cap
  re-anchor on the GCS global view, and retry-around-dead-raylets so the
  chaos scenario can kill nodes mid-chain.
- ``SimNodeProvider`` adapts the harness to the autoscaler's node-provider
  interface (``create_node``/``terminate_node``/``raylet_node_id``) so the
  scaling loop can be exercised against hundreds of fake nodes.

Everything here is test/bench infrastructure: nothing imports it from the
runtime paths.
"""

from __future__ import annotations

import asyncio
import logging
import os
import threading
from typing import Dict, List, Optional, Tuple

from ray_tpu._private import rpc
from ray_tpu._private.common import ResourceSet, config
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.ids import fast_unique_hex
from ray_tpu._private.raylet import Raylet

logger = logging.getLogger(__name__)

# Applied for the lifetime of the harness (restored on shutdown): periodic
# machinery that is per-node O(N) noise at hundreds of nodes — memory
# monitoring, store prefault, active health probes — is switched off, and
# GCS head broadcasts are batched so the fan-out is bounded by
# subscribers/batch_ms instead of subscribers*grants (common.py
# ``scheduler_view_batch_ms``). 200ms staleness is immaterial for picks
# (availability is also checked at the grant site) but the sim folds every
# subscriber's decode onto ONE loop thread, so the window directly scales
# harness throughput. Death detection still works with probing off: a
# killed raylet's GCS connection drop triggers _handle_node_death.
_SIM_ENV_DEFAULTS = {
    "RAY_TPU_MEMORY_MONITOR_INTERVAL_S": "0",
    "RAY_TPU_PREFAULT_OBJECT_STORE": "0",
    "RAY_TPU_HEALTH_CHECK_PERIOD_S": "0",
    "RAY_TPU_SCHEDULER_VIEW_BATCH_MS": "200",
    # Sim raylets host no real object churn: the default 0.25s pressure
    # poll is 2000 wakeups/s of pure timer noise at 500 nodes. Slower poll,
    # same behavior (sims that do spill just react within 2s).
    "RAY_TPU_OBJECT_SPILLING_POLL_INTERVAL_S": "2",
}

# Raylets booted concurrently during start(). Each boot is a server bind +
# GCS register + arena create; unbounded gather at 1000 nodes stampedes
# the accept queue and the allocator.
_BOOT_CONCURRENCY = 32


def _raise_nofile_limit(want: int) -> None:
    """Each sim raylet holds ~4 fds (listen socket, GCS conn both ends,
    arena shm): at 1000 nodes the default soft RLIMIT_NOFILE of 1024 is
    exhausted mid-boot. Raise it toward the hard limit; best-effort."""
    try:
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        if soft < want:
            resource.setrlimit(
                resource.RLIMIT_NOFILE, (min(want, hard), hard)
            )
    except (ImportError, ValueError, OSError):
        logger.warning("could not raise RLIMIT_NOFILE; large sims may fail")


class SimCluster:
    """N in-process raylets + a real GCS on a private event-loop thread.

    Synchronous drivers (pytest, ray_perf) call ``run(coro)`` to execute
    coroutines on the sim loop. The attribute surface matches what
    ``chaos.invariants`` and ``chaos.nemesis`` expect of a cluster:
    ``raylets`` (node_id -> Raylet), ``gcs_server``, and ``head_node``
    (None — every sim node is fair game for the nemesis).
    """

    def __init__(
        self,
        num_nodes: int,
        resources: Optional[Dict[str, float]] = None,
        object_store_memory: int = 1 << 20,
        env: Optional[Dict[str, str]] = None,
        persist_path: Optional[str] = None,
        ha: bool = False,
    ):
        self.num_nodes = num_nodes
        self.resources = resources or {"CPU": 4.0}
        self.object_store_memory = object_store_memory
        self.persist_path = persist_path
        # HA mode: replicated store + warm standby + leader pointer file, so
        # kill_gcs_host_async() can lose the "machine" holding the primary
        # log and fail over (docs/fault_tolerance.md "HA deployment").
        self.ha = ha
        if ha and not persist_path:
            raise ValueError("ha=True requires persist_path")
        self.gcs_standby = None
        self.session_name = f"sim-{fast_unique_hex()[:8]}"
        self.raylets: Dict[str, Raylet] = {}
        self.gcs_server: Optional[GcsServer] = None
        self.gcs_addr: Optional[Tuple[str, int]] = None
        self.head_node = None
        self._env = dict(_SIM_ENV_DEFAULTS)
        if env:
            self._env.update(env)
        self._saved_env: Dict[str, Optional[str]] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "SimCluster":
        for k, v in self._env.items():
            self._saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        config.refresh()
        _raise_nofile_limit(4 * self.num_nodes + 512)

        rpc.install_event_loop()
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop_main, name="sim-cluster-loop", daemon=True
        )
        self._thread.start()
        self.run(self._start_async(), timeout=max(120.0, self.num_nodes))
        return self

    def _loop_main(self) -> None:
        asyncio.set_event_loop(self._loop)
        self._loop.run_forever()

    def run(self, coro, timeout: float = 60.0):
        """Run a coroutine on the sim loop from the driving thread."""
        return asyncio.run_coroutine_threadsafe(coro, self._loop).result(
            timeout
        )

    async def _start_async(self) -> None:
        # persist_path=None (the default) -> in-memory GCS store; sim
        # sessions are throwaway and store churn at 1000 registrations is
        # pure tax. The chaos recovery scenarios pass a path so crash_gcs
        # has durable state to recover from.
        self.gcs_server = GcsServer(
            session_name=self.session_name,
            persist_path=self.persist_path,
            persist_backend="replicated" if self.ha else None,
        )
        self.gcs_addr = await self.gcs_server.start()
        if self.ha:
            await self._arm_standby()
        sem = asyncio.Semaphore(_BOOT_CONCURRENCY)

        async def boot(_i: int) -> None:
            async with sem:
                await self._add_node_async(dict(self.resources))

        await asyncio.gather(*(boot(i) for i in range(self.num_nodes)))

    async def _add_node_async(
        self, resources: Dict[str, float]
    ) -> Raylet:
        raylet = Raylet(
            self.gcs_addr,
            self.session_name,
            resources=resources,
            object_store_memory=self.object_store_memory,
            sim_workers=True,
            gcs_leader_file=self.gcs_leader_file(),
        )
        await raylet.start()
        self.raylets[raylet.node_id] = raylet
        return raylet

    def add_node(self, resources: Optional[Dict[str, float]] = None) -> Raylet:
        return self.run(
            self._add_node_async(dict(resources or self.resources)),
            timeout=60.0,
        )

    def remove_node(self, node_id: str) -> None:
        raylet = self.raylets.pop(node_id, None)
        if raylet is not None:
            self.run(raylet.stop(), timeout=60.0)

    def gcs_leader_file(self) -> Optional[str]:
        if not self.ha:
            return None
        from ray_tpu._private import gcs_ha

        return gcs_ha.leader_file_path(self.persist_path)

    async def _arm_standby(self) -> None:
        from ray_tpu._private.gcs_ha import GcsStandby

        self.gcs_standby = GcsStandby(
            session_name=self.session_name, persist_path=self.persist_path
        )
        await self.gcs_standby.start()

    async def kill_gcs_host_async(self, timeout: float = 30.0) -> bool:
        """Lose the GCS *machine*: hard-crash the process and drop its local
        log member (the disk went with the host), then wait for the warm
        standby to promote over the surviving follower log at term+1. The
        leader pointer file re-targets raylets on their next redial.
        Returns False when HA is off or the GCS is already gone."""
        if not self.ha or self.gcs_server is None or self.gcs_standby is None:
            return False
        from ray_tpu._private.gcs_store import drop_host

        await self.gcs_server.crash()
        drop_host(self.persist_path)
        return await self.adopt_promoted_gcs_async(timeout)

    async def adopt_promoted_gcs_async(self, timeout: float = 30.0) -> bool:
        """Wait for the armed standby to promote, adopt its server, and
        re-arm. Shared tail of kill_gcs_host_async, also used standalone
        when the leader demoted itself (lost its replication majority)."""
        if self.gcs_standby is None:
            return False
        await asyncio.wait_for(self.gcs_standby.promoted.wait(), timeout)
        self.gcs_server = self.gcs_standby.server
        self.gcs_addr = self.gcs_server.server.address
        await self._arm_standby()
        return True

    async def crash_gcs_async(self, torn_tail: bool = True) -> bool:
        """Hard-crash the GCS (no store checkpoint/fsync, optionally a torn
        WAL tail) and restart it on the same address from the persisted
        state. Raylets re-register over their reconnect loops. Returns
        False when the sim has no GCS (already shut down)."""
        if self.gcs_server is None or self.gcs_addr is None:
            return False
        await self.gcs_server.crash()
        if torn_tail and self.persist_path:
            from ray_tpu._private.gcs_store import inject_torn_tail

            inject_torn_tail(self.persist_path)
        self.gcs_server = GcsServer(
            host=self.gcs_addr[0],
            port=self.gcs_addr[1],
            session_name=self.session_name,
            persist_path=self.persist_path,
            persist_backend="replicated" if self.ha else None,
        )
        await self.gcs_server.start()
        return True

    def shutdown(self) -> None:
        if self._loop is None:
            return
        try:
            self.run(self._stop_async(), timeout=120.0)
        finally:
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=30.0)
            self._loop.close()
            self._loop = None
            for k, old in self._saved_env.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
            config.refresh()

    async def _stop_async(self) -> None:
        raylets = list(self.raylets.values())
        self.raylets.clear()
        sem = asyncio.Semaphore(_BOOT_CONCURRENCY)

        async def stop_one(r: Raylet) -> None:
            async with sem:
                try:
                    await r.stop()
                except Exception:
                    pass

        await asyncio.gather(*(stop_one(r) for r in raylets))
        if self.gcs_standby is not None:
            if self.gcs_standby.server is self.gcs_server:
                self.gcs_standby.server = None
            await self.gcs_standby.stop()
            self.gcs_standby = None
        if self.gcs_server is not None:
            await self.gcs_server.stop()
            self.gcs_server = None

    # -- conveniences --------------------------------------------------------

    def node_stats(self) -> List[dict]:
        """Per-node GetNodeStats rows, collected in-process — the
        autoscaler's ``state_fn`` for a driverless sim cluster."""

        async def collect() -> List[dict]:
            return [
                await r._node_stats(None, {})
                for r in list(self.raylets.values())
            ]

        return self.run(collect(), timeout=60.0)

    def any_addr(self) -> Tuple[str, int]:
        """Address of an arbitrary live raylet (lease entry point)."""
        raylet = next(iter(self.raylets.values()))
        return tuple(raylet.addr)

    def node_addr(self, node_id: str) -> Tuple[str, int]:
        return tuple(self.raylets[node_id].addr)


class SimLeaseClient:
    """Drives the lease protocol like ``core_worker._request_lease`` does,
    without a CoreWorker: follows spillback chains with ``spilled_from``
    pinning, re-anchors on the GCS global view when the hop cap trips, and
    — beyond what core_worker needs — retries around raylets that die
    mid-chain, for the chaos ``sched`` scenario. All methods are
    coroutines meant to run on the sim loop (``cluster.run``)."""

    def __init__(self, cluster: SimCluster, job_id: str = "simjob"):
        self.cluster = cluster
        self.job_id = job_id
        self._conns: Dict[Tuple[str, int], rpc.Connection] = {}
        self._gcs_conn: Optional[rpc.Connection] = None

    async def close(self) -> None:
        for conn in self._conns.values():
            await conn.close()
        self._conns.clear()
        if self._gcs_conn is not None:
            await self._gcs_conn.close()
            self._gcs_conn = None

    async def _conn_to(self, addr: Tuple[str, int]) -> rpc.Connection:
        key = (addr[0], addr[1])
        conn = self._conns.get(key)
        if conn is None or conn.closed:
            conn = await rpc.connect(*key)
            self._conns[key] = conn
        return conn

    async def _gcs(self) -> rpc.Connection:
        if self._gcs_conn is None or self._gcs_conn.closed:
            self._gcs_conn = await rpc.connect(*self.cluster.gcs_addr)
        return self._gcs_conn

    async def _gcs_pick(
        self, resources: Dict[str, int]
    ) -> Optional[Tuple[str, int]]:
        """Least-utilized ALIVE node whose totals fit the demand, from the
        GCS global view (mirrors core_worker._gcs_spill_target)."""
        try:
            reply = await (await self._gcs()).call("GetAllNodes")
        except rpc.RpcError:
            return None
        demand = ResourceSet.from_units(resources)
        best_addr = None
        best_util = None
        for n in reply["nodes"]:
            if n.get("state") != "ALIVE":
                continue
            total = ResourceSet.from_units(n.get("total") or {})
            if not demand.is_subset_of(total):
                continue
            tot = n.get("total") or {}
            avail = n.get("available") or {}
            util = max(
                (
                    1.0 - avail.get(r, 0) / t
                    for r, t in tot.items()
                    if t and not r.startswith("node:")
                ),
                default=0.0,
            )
            if best_util is None or util < best_util:
                best_util = util
                best_addr = tuple(n["addr"])
        return best_addr

    async def lease(
        self,
        resources: Dict[str, int],
        entry_addr: Optional[Tuple[str, int]] = None,
        strategy: Optional[dict] = None,
        locality: Optional[Dict[str, float]] = None,
        timeout: Optional[float] = 60.0,
    ) -> dict:
        """One lease grant: {"lease_id", "addr", "worker_id"}. ``addr`` is
        the granting raylet — pass the dict to release(). ``resources`` is
        a float amount dict ({"CPU": 1.0}); the wire carries fixed-point
        units like every other producer."""
        units = ResourceSet(resources).to_units()
        lease_id = fast_unique_hex()
        addr = tuple(entry_addr or self.cluster.any_addr())
        hops = 0
        used_gcs_fallback = False
        while True:
            try:
                conn = await self._conn_to(addr)
                # Batched: every lease op this client issues to the same
                # raylet in one loop tick rides a single LeaseBatch frame.
                reply = await conn.call_batched(
                    "RequestWorkerLease",
                    {
                        "lease_id": lease_id,
                        "resources": units,
                        "strategy": strategy,
                        "spilled_from": hops > 0,
                        "locality": locality if hops == 0 else None,
                        "job_id": self.job_id,
                    },
                    timeout=timeout,
                )
            except rpc.RpcError:
                # The target raylet died under us (chaos kill mid-chain).
                # Its ledger died with it, so the same lease_id is safe to
                # re-anchor elsewhere; pick via the GCS view, pinned so the
                # survivor queues instead of re-bouncing.
                self._conns.pop(addr, None)
                target = await self._gcs_pick(units)
                if target is None or target == addr:
                    raise
                addr = target
                hops = max(hops, 1)
                continue
            if reply.get("granted"):
                return {
                    "lease_id": reply["lease_id"],
                    "addr": addr,
                    "worker_id": reply["worker_id"],
                }
            if reply.get("cancelled"):
                raise rpc.RpcError(f"lease {lease_id} cancelled")
            spill = reply.get("spillback")
            if spill is None:
                raise rpc.RpcError(
                    f"no node can host resources {resources} "
                    "(cluster infeasible)"
                )
            hops += 1
            if hops > 4:
                if used_gcs_fallback:
                    raise rpc.RpcError(
                        "lease spillback loop exceeded 4 hops after "
                        "GCS-view fallback"
                    )
                used_gcs_fallback = True
                target = await self._gcs_pick(units)
                if target is None:
                    raise rpc.RpcError(
                        f"no node can host resources {resources} "
                        "(cluster infeasible)"
                    )
                addr = target
                hops = 1
                continue
            addr = tuple(spill["addr"])

    async def release(self, grant: dict, dirty: bool = False) -> bool:
        """Return the leased worker. False when the granting raylet is
        gone — its lease table died with it, nothing left to release."""
        try:
            conn = await self._conn_to(tuple(grant["addr"]))
            await conn.call_batched(
                "ReturnWorker",
                {"lease_id": grant["lease_id"], "dirty": dirty},
            )
            return True
        except rpc.RpcError:
            return False

    async def lease_cycle(
        self,
        resources: Dict[str, int],
        entry_addr: Optional[Tuple[str, int]] = None,
        hold_s: float = 0.0,
        **kw,
    ) -> dict:
        grant = await self.lease(resources, entry_addr, **kw)
        if hold_s > 0:
            await asyncio.sleep(hold_s)
        await self.release(grant)
        return grant


class SimNodeProvider:
    """Autoscaler node provider backed by a SimCluster: create_node boots
    a real sim raylet on the sim loop, terminate_node stops it. Thread
    context: the autoscaler calls these synchronously from its own thread;
    they block on ``cluster.run``."""

    def __init__(
        self,
        cluster: SimCluster,
        node_types: Optional[Dict[str, dict]] = None,
    ):
        self.cluster = cluster
        self.node_types = node_types or {
            "sim.cpu4": {"resources": {"CPU": 4}, "max_workers": 2000},
        }
        self._pids: Dict[str, str] = {}  # provider pid -> raylet node_id
        self._seq = 0

    def create_node(self, node_type: str) -> str:
        spec = self.node_types[node_type]
        resources = {
            k: float(v) for k, v in spec.get("resources", {}).items()
        }
        raylet = self.cluster.add_node(resources=resources)
        self._seq += 1
        pid = f"sim-{self._seq}"
        self._pids[pid] = raylet.node_id
        return pid

    def terminate_node(self, pid: str) -> bool:
        node_id = self._pids.pop(pid, None)
        if node_id is None:
            return False
        self.cluster.remove_node(node_id)
        return True

    def raylet_node_id(self, pid: str) -> Optional[str]:
        return self._pids.get(pid)

    def failed_nodes(self) -> List[str]:
        return []

    def forget_node(self, pid: str) -> None:
        self._pids.pop(pid, None)
