"""Asyncio RPC layer: streaming msgpack frames over TCP.

TPU-native analog of the reference's rpc scaffolding (src/ray/rpc/): persistent
client connections with call multiplexing, a handler-registry server, and
server->client push for pubsub channels. The reference wraps gRPC; we use a
lean custom framing because every daemon here is an asyncio program and the
control-plane messages are small dicts — msgpack round-trips them with no
codegen step. Payloads that carry Python objects (task args, actor state)
are cloudpickled into opaque ``bytes`` fields by the caller.

Wire format: a raw msgpack stream; each message is ``[msgid, kind, method,
payload]``. Kinds: 0=request, 1=reply, 2=error-reply, 3=push (one-way),
4=blob (one-way when msgid==0, request otherwise), 5=blob-reply.
Requests may carry a fifth element: the remaining deadline budget (TTL) in
float seconds, stamped at the moment the frame is packed. The receiver
reconstructs an absolute deadline on its own clock (``loop.time() + ttl``)
— relative TTLs make the deadline clock-skew-free, and a frame a fault
schedule holds back arrives with its budget already shrunk. msgpack is
self-framing, so no length prefix is needed — the receiving side feeds
whole socket chunks to a streaming Unpacker and drains every complete
message per chunk with zero per-frame awaits.

Blob sidecar frames (kinds 4/5) are the zero-copy data plane: the control
frame is packed msgpack like any other, but its fifth element declares a
byte length and the next N bytes on the stream are the raw payload,
UN-packed. The sender hands ``memoryview``s straight to the transport (no
pack copy, no join); the receiver switches the read loop into blob mode
and streams the bytes into a *sink* — for object transfer that sink is
the destination shm arena at the object's assigned offset, so a remote
transfer costs one copy (socket -> arena), same as a local put. Sinks are
chosen per method (``Server.register_blob``), per call
(``Connection.call_into``), or default to an in-memory buffer delivered
to the regular handler as ``payload["data"]``.

Resilience (reference: retryable_grpc_client.h / gcs_rpc_client.h): every
``call`` with a timeout (explicit or inherited from the ambient handler
deadline) propagates its remaining budget downstream, so GCS -> raylet ->
worker chains shrink the budget at every hop and no hop outlives its
caller; servers shed requests that arrive already expired and cancel
handlers at their deadline. :class:`RetryPolicy` (full-jitter exponential
backoff with attempt + total-budget caps) drives both the ``connect`` dial
loop and :class:`RetryableConnection`, which re-dials dead links and
re-issues calls whose method the wire registry declares retry-safe.

Throughput design (reference: the C++ layer's batched stream writes in
ClientCallManager): the hot path is callback-based, not coroutine-based.
``call_nowait`` appends a pre-packed frame to a per-connection out-buffer and
schedules ONE flush per event-loop tick (``call_soon``), collapsing any number
of pipelined requests into a single ``transport.write`` syscall; replies are
dispatched inline from ``data_received``. ``call``/``push`` remain the
coroutine conveniences on top.
"""

from __future__ import annotations

import asyncio
import contextvars
import itertools
import logging
import os
import random
import tempfile
import traceback
from dataclasses import dataclass
from typing import Any, Awaitable, Callable, Dict, Iterator, List, Optional, Tuple

import msgpack

from ray_tpu._private import telemetry
from ray_tpu._private.common import config


def _uds_path(port: int) -> str:
    return os.path.join(tempfile.gettempdir(), f"ray_tpu_uds_{port}.sock")


_LOOPBACK = frozenset({"127.0.0.1", "localhost", "::1"})

logger = logging.getLogger(__name__)

# The event loop holds only weak references to tasks: a fire-and-forget
# asyncio.create_task() whose result is dropped can be garbage-collected
# mid-flight (observed as lease requests silently vanishing under GC
# pressure). Every background task in the runtime goes through spawn(),
# which parks a strong reference until the task completes.
_BG_TASKS: set = set()


def spawn(coro) -> asyncio.Task:
    # The one sanctioned create_task call site: spawn() IS the wrapper the
    # raw-create-task rule points everyone at.
    task = asyncio.get_running_loop().create_task(coro)  # aio-lint: disable=raw-create-task
    _BG_TASKS.add(task)
    task.add_done_callback(_BG_TASKS.discard)
    return task


_KIND_REQ = 0
_KIND_REP = 1
_KIND_ERR = 2
_KIND_PUSH = 3
# Blob sidecar frames: the packed control message is [msgid, kind, method,
# payload, blob_len] and the blob_len bytes that follow on the stream are raw
# (not msgpack). kind 4 is one-way when msgid == 0 (PushChunk) and a request
# otherwise; kind 5 is a reply whose bulk data rides as the sidecar.
_KIND_BLOB = 4
_KIND_BLOB_REP = 5

_MAX_FRAME = 1 << 31

# msgpack fixarray headers (frames are 4-6 slots, always < 16): used when
# splicing a PackedPayload into a hand-assembled frame.
_FIXARRAY = [bytes([0x90 | i]) for i in range(16)]

# Per-kind frame/byte counters, cells bound once at import (indexable by the
# wire kind, so the send/receive hot paths do one list index + float add).
# Blob kinds count the sidecar bytes too — the data plane is the point.
_KIND_NAMES = ("req", "rep", "err", "push", "blob", "blob_rep")
_TEL_FRAMES_OUT = [
    telemetry.counter(
        "rpc", "frames_sent", "frames written, by wire kind"
    ).cell(kind=k)
    for k in _KIND_NAMES
]
_TEL_BYTES_OUT = [
    telemetry.counter(
        "rpc", "bytes_sent", "wire bytes written (control + blob sidecars)"
    ).cell(kind=k)
    for k in _KIND_NAMES
]
_TEL_FRAMES_IN = [
    telemetry.counter(
        "rpc", "frames_received", "frames decoded, by wire kind"
    ).cell(kind=k)
    for k in _KIND_NAMES
]
_TEL_BYTES_IN = telemetry.counter(
    "rpc", "bytes_received", "raw socket bytes received"
)
_TEL_DL_MET = telemetry.counter(
    "rpc", "deadline_met", "handlers finished inside their wire deadline"
)
_TEL_DL_SHED = telemetry.counter(
    "rpc", "deadline_shed", "requests dropped as already expired"
)
_TEL_DL_ENFORCED = telemetry.counter(
    "rpc", "deadline_enforced", "handlers cancelled at their wire deadline"
)
_TEL_DL_OVERRUNS = telemetry.counter(
    "rpc", "deadline_overruns", "handlers that outlived deadline + grace"
)

# _flush joins adjacent small buffers into one transport.write; buffers at or
# above this size are written individually so large blob memoryviews go to
# the socket without an intermediate join copy.
_WRITE_JOIN_MAX = 64 * 1024


def _blob_buffers(blob) -> list:
    """Normalize a blob argument (bytes/bytearray/memoryview or a list of
    them) into a flat list of 1-D byte memoryviews."""
    parts = [blob] if isinstance(blob, (bytes, bytearray, memoryview)) else list(blob)
    out = []
    for p in parts:
        v = p if isinstance(p, memoryview) else memoryview(p)
        if v.format != "B" or v.ndim != 1:
            v = v.cast("B")
        if v.nbytes:
            out.append(v)
    return out


def _blob_bytes(blob) -> bytes:
    """Materialize a blob into one stable bytes object (chaos interception:
    a delayed/duplicated frame must not reference live arena memory)."""
    bufs = _blob_buffers(blob)
    if len(bufs) == 1:
        return bytes(bufs[0])
    return b"".join(bufs)


class Blob:
    """Handler return value that ships as a blob-reply frame: ``payload`` is
    the msgpack meta, ``blob`` (bytes/memoryview or list of them) rides the
    stream raw. The buffers are written to the transport before the send
    call returns, so handlers may pass live arena views."""

    __slots__ = ("payload", "blob")

    def __init__(self, payload: Any, blob):
        self.payload = payload
        self.blob = blob


class BufferSink:
    """Default blob sink: accumulates the inbound blob into one buffer.
    ``value()`` returns the filled bytearray without a final copy."""

    __slots__ = ("_buf", "_pos")

    def __init__(self, size: int):
        self._buf = bytearray(size)
        self._pos = 0

    def write(self, view: memoryview) -> None:
        n = view.nbytes
        self._buf[self._pos : self._pos + n] = view
        self._pos += n

    def done(self, ok: bool) -> None:
        pass

    def value(self) -> bytearray:
        return self._buf


class _NullSink:
    """Discards an unwanted blob (declined by a sink factory) so the stream
    stays framed."""

    __slots__ = ()

    def write(self, view: memoryview) -> None:
        pass

    def done(self, ok: bool) -> None:
        pass


class SpanSink:
    """Blob sink writing sequentially into a caller-held memoryview span
    (e.g. an shm arena slice at an object's assigned offset)."""

    __slots__ = ("view", "pos", "written")

    def __init__(self, view: memoryview, pos: int = 0):
        self.view = view
        self.pos = pos
        self.written = 0

    def write(self, v: memoryview) -> None:
        n = v.nbytes
        self.view[self.pos : self.pos + n] = v
        self.pos += n
        self.written += n

    def done(self, ok: bool) -> None:
        pass

# Fault-injection hook (ray_tpu.chaos): when set, every outbound frame from
# this process is offered to the interceptor BEFORE packing. The interceptor
# returns True to consume the frame (drop it, or re-deliver it later /
# duplicated / reordered via ``Connection._send_direct``) and False to let it
# flow normally. One module-global — not per-Connection — so a chaos schedule
# covers every link in the process (GCS, raylets, driver core) without the
# daemons knowing chaos exists. None (the default) costs one global read per
# frame on the hot path. Loop thread only, like every send.
_send_interceptor: Optional[Callable[["Connection", list], bool]] = None


def set_send_interceptor(fn: Optional[Callable[["Connection", list], bool]]) -> None:
    """Install (or clear, with None) the process-wide outbound-frame
    interceptor. Test/chaos tooling only; never used in production paths."""
    global _send_interceptor
    _send_interceptor = fn


def get_send_interceptor() -> Optional[Callable[["Connection", list], bool]]:
    return _send_interceptor


def pack_push(method: str, payload: Any = None) -> Optional[bytes]:
    """Pre-pack a one-way frame for fan-out via
    ``Connection.push_packed_nowait``. Returns None while a fault
    interceptor is installed: pre-packed bytes would bypass it, and a chaos
    schedule must see (and be able to drop/delay) every individual frame."""
    if _send_interceptor is not None:
        return None
    frame = [0, _KIND_PUSH, method, payload]
    if method in _native_methods():
        if _NATIVE_WIRE is not None:
            try:
                packed = _NATIVE_WIRE.pack_frame(frame)
                _TEL_NATIVE_PACK.inc()
                return packed
            except Exception:
                pass  # unexpected payload shape: fall through to msgpack
        _TEL_FALLBACK_PACK.inc()
    return _packb(frame)


# Sentinel error string delivered to call_cb callbacks on connection loss
# (distinguishes transport death from a handler-level error reply).
_CONNECTION_LOST = "__connection_lost__"


class RpcError(Exception):
    """Raised on the caller when the remote handler raised or the link died."""


class ConnectionLost(RpcError):
    pass


class DeadlineExceeded(RpcError):
    """A request arrived past its deadline (shed) or its handler was cut at
    the deadline. The error-reply text starts with this class name so the
    far side can tell budget exhaustion from a handler bug."""


class StaleLeaderError(RpcError):
    """A write carried a leader term older than the store's fence: the
    issuing GCS lost leadership (lease expired, standby promoted) and must
    not mutate control-plane state. Raised server-side by the replicated
    store and surfaced to clients as a typed error so callers can
    re-resolve the leader instead of retrying a doomed write."""


# Error-reply payloads are ``f"{type(e).__name__}: {e}"`` plus traceback;
# these prefixes re-type the caller-side exception so control flow (leader
# fencing, deadline budgeting) doesn't have to string-match at every site.
# Only RpcError subclasses belong here: callers' ``except RpcError`` blocks
# must keep catching every wire-level failure. Schemas in wire.py declare
# which of these (plus the RayTpuError family, which crosses inside reply
# payloads, not error frames) each method's handler can raise — the
# exc_flow lint pass keeps the declarations honest.
_TYPED_ERRORS = {
    "StaleLeaderError:": StaleLeaderError,
    "DeadlineExceeded:": DeadlineExceeded,
}


def _typed_error(payload) -> RpcError:
    if isinstance(payload, str):
        for prefix, cls in _TYPED_ERRORS.items():
            if payload.startswith(prefix):
                return cls(payload)
    return RpcError(payload)


_packb = msgpack.Packer(use_bin_type=True, autoreset=True).pack


# ---------------------------------------------------------------------------
# Native wire codec (src/fastpath.cc, ray_tpu._native._fastpath).
#
# The hottest schemas — registered per-method in wire.NATIVE_WIRE_SCHEMAS —
# are packed by a C encoder that emits byte-identical msgpack (the parity
# fuzz in tests/test_fastpath_native.py holds both directions), and the
# whole inbound stream is decoded by a C streaming decoder with the same
# feed()/iterate/tell() surface as msgpack.Unpacker. Three ways back to the
# pure-Python path: the .so is absent (source checkout, masked import),
# RAY_TPU_NATIVE_WIRE=0, or the compiled schema versions disagree with
# wire.py (a drift the `wire-native-drift` lint rule catches at review
# time; the runtime check keeps a stale .so safe anyway).
# ---------------------------------------------------------------------------

_NATIVE_WIRE = None
if os.environ.get("RAY_TPU_NATIVE_WIRE", "1") != "0":  # pragma: no branch
    try:
        from ray_tpu._native import _fastpath as _native_mod

        if hasattr(_native_mod, "pack_frame") and hasattr(_native_mod, "Decoder"):
            _NATIVE_WIRE = _native_mod
    except Exception:  # pragma: no cover - source checkout without the .so
        _NATIVE_WIRE = None

# Methods eligible for native pack: resolved lazily from wire.py (rpc.py is
# the bottom of the import graph and cannot import wire at module load).
# None = not resolved yet; frozenset once resolved.
_NATIVE_METHODS: Optional[frozenset] = None


def _native_methods() -> frozenset:
    global _NATIVE_METHODS
    if _NATIVE_METHODS is None:
        try:
            from ray_tpu._private import wire  # lazy: avoid import cycle

            _NATIVE_METHODS = wire.native_method_set(_NATIVE_WIRE)
        except Exception:  # pragma: no cover - wire must stay importable
            logger.exception("native wire schema resolution failed")
            _NATIVE_METHODS = frozenset()
    return _NATIVE_METHODS


def native_wire_active() -> bool:
    """True when the C codec is loaded and at least one schema is bound."""
    return _NATIVE_WIRE is not None and bool(_native_methods())


_TEL_NATIVE_PACK = telemetry.counter(
    "rpc", "native_pack", "frames packed by the native (C) wire codec"
)
_TEL_FALLBACK_PACK = telemetry.counter(
    "rpc",
    "fallback_pack",
    "native-registered frames packed by Python msgpack instead "
    "(.so absent, RAY_TPU_NATIVE_WIRE=0, or a pack error)",
)
_TEL_BATCH_SIZE = telemetry.histogram(
    "rpc",
    "lease_batch_size",
    "entries coalesced per flushed lease batch (1 = singleton fast frame)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128),
)


class PackedPayload(dict):
    """A payload carrying its own msgpack bytes, spliced verbatim into the
    frame by ``_pack_frame`` — the grant fan-out hot path: a raylet
    granting N queued leases packs the common reply skeleton once and
    patches per-lease fields, instead of paying a full dict encode per
    grant. Subclasses dict so in-process consumers (explorer scenarios,
    tests that call handlers directly) read it like the payload it encodes;
    ``raw`` MUST be exactly one msgpack value encoding the same mapping,
    and the mapping must not be mutated after construction (the bytes
    would go stale)."""

    __slots__ = ("raw",)

    def __init__(self, mapping: dict, raw: bytes):
        super().__init__(mapping)
        self.raw = raw


def _cancel_for_timeout(fut: asyncio.Future) -> None:
    """Deadline timer callback for Connection.call: mark-then-cancel so the
    awaiter can tell a timeout from a caller cancellation."""
    if not fut.done():
        fut.rpc_timed_out = True
        fut.cancel()


def install_event_loop() -> str:
    """Install the event-loop policy named by ``config.rpc_event_loop``.

    Returns the name actually in effect. "uvloop" requires the package;
    when it is not importable (this tree does not vendor it) the stock
    asyncio policy stays installed and a log line records the fallback, so
    the knob is safe to flip in config without a hard dependency."""
    choice = getattr(config, "rpc_event_loop", "asyncio")
    if choice == "uvloop":
        try:
            import uvloop  # type: ignore

            uvloop.install()
            return "uvloop"
        except ImportError:
            logger.info(
                "rpc_event_loop=uvloop requested but uvloop is not "
                "installed; using asyncio"
            )
    return "asyncio"


# ---------------------------------------------------------------------------
# End-to-end deadlines.
#
# The deadline of the request currently being dispatched, as an absolute
# loop.time() instant, set per handler task (each dispatch runs in its own
# task, whose context copy isolates the var). Any ``Connection.call`` made
# under it inherits the remaining budget — the mechanism by which a 120 s
# LeaseWorkerForActor clamps the CreateActor it fans out to.
# ---------------------------------------------------------------------------

_ambient_deadline: contextvars.ContextVar[Optional[float]] = contextvars.ContextVar(
    "ray_tpu_rpc_deadline", default=None
)


def current_deadline() -> Optional[float]:
    """Absolute loop-time deadline of the request being handled, if any."""
    return _ambient_deadline.get()


def remaining_budget() -> Optional[float]:
    """Seconds left in the current handler's deadline budget (None if
    unbounded). Loop thread only."""
    deadline = _ambient_deadline.get()
    if deadline is None:
        return None
    return deadline - asyncio.get_running_loop().time()


# ---------------------------------------------------------------------------
# Trace-context propagation.
#
# The (trace_id, span_id) of the active tracing span, riding request frames
# exactly like the deadline TTL: stamped by the sender when set, restored
# around the handler on the receiving side (per dispatch task — same
# context-copy isolation as ``_ambient_deadline``). The var lives HERE, not
# in util/tracing.py, because this module is the bottom of the import graph
# (tracing builds on it; importing util from rpc would cycle through the
# worker stack). ``ray_tpu.util.tracing`` owns everything above the raw
# contextvar: span recording, sampling, flushing, scopes.
# ---------------------------------------------------------------------------

_trace_ctx: contextvars.ContextVar[Optional[tuple]] = contextvars.ContextVar(
    "ray_tpu_trace_ctx", default=None
)


def current_trace_ctx() -> Optional[tuple]:
    """(trace_id, span_id) of the active span, or None."""
    return _trace_ctx.get()


class DeadlineStats:
    """Process-wide counters for deadline enforcement; the chaos runner
    resets them per seed and the no-call-outlives-deadline invariant reads
    ``overruns`` (handlers that survived past deadline + grace — a stalled
    loop or a handler swallowing cancellation)."""

    __slots__ = ("met", "shed", "enforced", "overruns")

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self.met = 0          # handlers that finished inside their deadline
        self.shed = 0         # requests dropped as already expired
        self.enforced = 0     # handlers cancelled at their deadline
        self.overruns: List[Tuple[str, float]] = []  # (method, seconds late)

    def snapshot(self) -> dict:
        return {
            "met": self.met,
            "shed": self.shed,
            "enforced": self.enforced,
            "overruns": list(self.overruns),
        }


deadline_stats = DeadlineStats()


# ---------------------------------------------------------------------------
# Retry policy (reference: retryable_grpc_client.h exponential backoff).
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class RetryPolicy:
    """Full-jitter exponential backoff with an attempt cap and a total
    wall-clock budget. ``max_attempts``/``total_budget_s`` of 0 mean
    unbounded on that axis (the other cap still applies)."""

    initial_backoff_s: float = 0.05
    max_backoff_s: float = 2.0
    multiplier: float = 2.0
    max_attempts: int = 0
    total_budget_s: float = 30.0

    def backoff_cap(self, retry_index: int) -> float:
        """Upper bound of the jitter window before retry ``retry_index``
        (0-based)."""
        return min(
            self.max_backoff_s,
            self.initial_backoff_s * self.multiplier ** retry_index,
        )

    def backoffs(self, rng: Optional[random.Random] = None) -> Iterator[float]:
        """Infinite stream of jittered sleeps: sleep_i ~ U(0, cap_i). Pass a
        seeded ``random.Random`` for a deterministic schedule (tests,
        replay); the caps bound the caller's loop via :meth:`allows`."""
        uniform = (rng or random).uniform
        i = 0
        while True:
            yield uniform(0.0, self.backoff_cap(i))
            i += 1

    def allows(self, attempt: int, elapsed_s: float) -> bool:
        """May attempt number ``attempt`` (1-based) start after
        ``elapsed_s`` seconds since the first try?"""
        if self.max_attempts > 0 and attempt > self.max_attempts:
            return False
        if self.total_budget_s > 0 and elapsed_s >= self.total_budget_s:
            return False
        return True

    @classmethod
    def for_dial(cls) -> "RetryPolicy":
        return cls(
            initial_backoff_s=config.rpc_dial_initial_backoff_s,
            max_backoff_s=config.rpc_dial_max_backoff_s,
            multiplier=config.rpc_backoff_multiplier,
            total_budget_s=config.rpc_dial_total_s,
        )

    @classmethod
    def for_calls(cls) -> "RetryPolicy":
        return cls(
            initial_backoff_s=config.rpc_retry_initial_backoff_s,
            max_backoff_s=config.rpc_retry_max_backoff_s,
            multiplier=config.rpc_backoff_multiplier,
            total_budget_s=config.rpc_reconnect_timeout_s,
        )


def _new_unpacker():
    """Streaming frame decoder: the native C decoder when loaded (same
    feed()/iterate/tell() surface, byte-identical results), else msgpack's.
    One per connection, plus a fresh one at every blob-mode switch."""
    if _NATIVE_WIRE is not None:
        return _NATIVE_WIRE.Decoder()
    return msgpack.Unpacker(
        raw=False, strict_map_key=False, max_buffer_size=_MAX_FRAME
    )


class _RpcProtocol(asyncio.Protocol):
    """Transport glue: buffers writes per loop tick, streams reads through a
    msgpack Unpacker, and forwards complete messages to the Connection."""

    def __init__(self, conn: "Connection"):
        self._conn = conn
        self._unpacker = _new_unpacker()
        self.transport: Optional[asyncio.Transport] = None
        self._paused = False
        self._drain_waiters: list = []
        # Blob receive mode: while _blob_remaining > 0 inbound bytes bypass
        # the Unpacker and stream into _blob_sink. _fed counts bytes fed to
        # the CURRENT Unpacker so the unconsumed tail (bytes after a blob
        # control frame) can be recovered via unpacker.tell().
        self._fed = 0
        self._blob_msg: Optional[list] = None
        self._blob_sink = None
        self._blob_external = False
        self._blob_remaining = 0

    def connection_made(self, transport) -> None:
        self.transport = transport

    def connection_lost(self, exc) -> None:
        for w in self._drain_waiters:
            if not w.done():
                w.set_result(None)
        self._drain_waiters.clear()
        self._conn._teardown()

    def pause_writing(self) -> None:
        self._paused = True

    def resume_writing(self) -> None:
        self._paused = False
        for w in self._drain_waiters:
            if not w.done():
                w.set_result(None)
        self._drain_waiters.clear()

    def data_received(self, data: bytes) -> None:
        _TEL_BYTES_IN.inc(len(data))
        view = memoryview(data)
        conn = self._conn
        # Replies produced while we dispatch this chunk (sync handlers
        # answering inline) are flushed once at the end of the read instead
        # of via a call_soon per reply: same coalescing, one less loop
        # callback per request on the server hot path.
        conn._in_read = True
        try:
            self._feed(view)
        finally:
            conn._in_read = False
            if conn._out and not conn._flush_scheduled and not conn._closed:
                conn._flush()

    def _feed(self, view) -> None:
        try:
            while True:
                if self._blob_remaining > 0:
                    n = view.nbytes
                    if n <= self._blob_remaining:
                        self._blob_sink.write(view)
                        self._blob_remaining -= n
                        if self._blob_remaining == 0:
                            self._finish_blob()
                        return
                    self._blob_sink.write(view[: self._blob_remaining])
                    view = view[self._blob_remaining :]
                    self._blob_remaining = 0
                    self._finish_blob()
                if not view.nbytes:
                    return
                self._unpacker.feed(view)
                self._fed += view.nbytes
                switched = False
                for msg in self._unpacker:
                    if (
                        isinstance(msg, (list, tuple))
                        and len(msg) >= 5
                        and (msg[1] == _KIND_BLOB or msg[1] == _KIND_BLOB_REP)
                    ):
                        # The bytes after this control frame are the raw blob
                        # (and whatever follows it), NOT msgpack: recover the
                        # unconsumed tail of the current chunk, discard the
                        # Unpacker (its buffer holds those same bytes), and
                        # switch to blob mode.
                        tail = self._fed - self._unpacker.tell()
                        self._unpacker = _new_unpacker()
                        self._fed = 0
                        self._begin_blob(list(msg))
                        view = view[view.nbytes - tail :]
                        switched = True
                        break
                    self._conn._on_message(msg)
                if not switched:
                    return
        except Exception:
            logger.exception("rpc stream corrupted; dropping connection")
            if self.transport is not None:
                self.transport.close()

    def _begin_blob(self, msg: list) -> None:
        size = msg[4]
        if not isinstance(size, int) or size < 0 or size > _MAX_FRAME:
            raise RpcError(f"invalid blob length {size!r}")
        _TEL_FRAMES_IN[msg[1]].inc()
        sink, external = self._conn._select_blob_sink(msg, size)
        if size == 0:
            self._conn._on_blob_complete(msg, sink, external)
            return
        self._blob_msg = msg
        self._blob_sink = sink
        self._blob_external = external
        self._blob_remaining = size

    def _finish_blob(self) -> None:
        msg, sink, external = self._blob_msg, self._blob_sink, self._blob_external
        self._blob_msg = None
        self._blob_sink = None
        self._conn._on_blob_complete(msg, sink, external)


class Connection:
    """One end of a duplex RPC link. Both sides can issue requests and pushes."""

    def __init__(
        self,
        handlers: Dict[str, Callable[..., Awaitable[Any]]],
        on_close: Optional[Callable[["Connection"], None]] = None,
        sync_handlers: Optional[Dict[str, Callable]] = None,
        blob_factories: Optional[Dict[str, Callable]] = None,
        dispatch_observer: Optional[Callable[[str, float], None]] = None,
    ):
        self._handlers = handlers
        # Optional ``(method, seconds)`` callback fired after each async
        # handler dispatch — the GCS attaches its service-latency histogram
        # here (telemetry.py). None (the default) costs one branch.
        self._dispatch_observer = dispatch_observer
        # Blob sink factories: ``factory(conn, payload, size) -> sink|None``
        # invoked inline from the read path when a kind-4 control frame for
        # that method arrives; None declines (the blob is drained and
        # discarded). Shared dict from the owning Server (register_blob).
        self._blob_factories = blob_factories if blob_factories is not None else {}
        # Per-call blob-reply sinks (call_into), keyed by msgid.
        self._blob_reply_sinks: Dict[int, Any] = {}
        # Sync fast-path handlers: ``fn(conn, msgid, payload)`` invoked inline
        # from data_received — no asyncio task per message. The handler must
        # not block; it replies later via ``reply_nowait``. Used for the task
        # execution hot path (reference analog: the C++ server's inlined
        # HandleRequest dispatch before posting to the io_context).
        self._sync_handlers = sync_handlers if sync_handlers is not None else {}
        self._on_close = on_close
        self._msgid = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        # Inline reply callbacks (call_cb): msgid -> cb(reply, error).
        self._cb_pending: Dict[int, Callable] = {}
        self._closed = False
        self._loop = asyncio.get_running_loop()
        self._protocol = _RpcProtocol(self)
        self._out: list = []
        self._flush_scheduled = False
        # True while data_received is dispatching inbound frames on this
        # connection: replies queued during the read are flushed at its end
        # (no call_soon per reply).
        self._in_read = False
        # Lease-batch coalescing (call_batched_nowait): entries queued for
        # the next flush tick. Each entry is [msgid, method, payload,
        # absolute_deadline|None, [trace_id, span_id]|None]; per-entry
        # msgids keep dedup tokens, cancellation, and chaos faults
        # operating per-lease inside the coalesced frame.
        self._batch_entries: list = []
        self._batch_scheduled = False
        # Arbitrary per-connection state daemons can attach (e.g. worker id).
        self.context: Dict[str, Any] = {}
        # The logical (host, port) this connection was dialed to; set by
        # connect(). Stays meaningful when the transport is a Unix socket.
        self.remote_addr: Optional[Tuple[str, int]] = None

    @property
    def peername(self) -> Optional[Tuple[str, int]]:
        if self.remote_addr is not None:
            return self.remote_addr
        try:
            name = self._protocol.transport.get_extra_info("peername")
        except Exception:
            return None
        if isinstance(name, tuple) and len(name) >= 2:
            return (name[0], name[1])
        return None

    # -- write path ----------------------------------------------------------

    def _pack_frame(self, msg) -> list:
        """Pack one frame into its wire buffers. For a request with a
        deadline, the absolute loop.time() instant held in-memory is stamped
        into the relative TTL that goes on the wire — at pack time, not call
        time, so a frame a chaos schedule delays ships with its budget
        already shrunk and the receiver's reconstructed deadline stays
        honest. A blob frame packs as its control message (payload slot 4
        rewritten to the byte length) followed by the raw buffers; blob
        frames never carry trace context (slot 4 is the byte length and the
        data plane is instrumented at its managers instead)."""
        kind = msg[1]
        if kind == _KIND_BLOB or kind == _KIND_BLOB_REP:
            buffers = _blob_buffers(msg[4])
            total = sum(b.nbytes for b in buffers)
            out = [_packb([msg[0], kind, msg[2], msg[3], total])]
            out.extend(buffers)
            _TEL_FRAMES_OUT[kind].inc()
            _TEL_BYTES_OUT[kind].inc(len(out[0]) + total)
            return out
        method = msg[2]
        if kind == _KIND_PUSH and method == "LeaseBatch":
            # Per-entry deadlines are absolute loop instants in memory;
            # stamp each into a relative TTL at pack time on a copy — the
            # same honesty rule as the frame-level slot, so a batch a chaos
            # schedule delays ships with every entry's budget already
            # shrunk (the in-memory frame keeps absolute instants and a
            # re-send re-stamps them).
            now = self._loop.time()
            entries = [
                [e[0], e[1], e[2], None if e[3] is None else e[3] - now, e[4]]
                for e in msg[3]["entries"]
            ]
            msg = [msg[0], kind, method, {"entries": entries}]
        elif len(msg) > 4 and msg[4] is not None:
            # Rebuild in place so a trailing trace-context slot survives.
            msg = list(msg)
            msg[4] = msg[4] - self._loop.time()
        payload = msg[3]
        if type(payload) is PackedPayload:
            # Splice pre-packed payload bytes into the frame: fixarray
            # header + per-slot packs around the raw value. The grant
            # fan-out path pays one skeleton pack for N replies.
            parts = [_FIXARRAY[len(msg)], _packb(msg[0]), _packb(kind),
                     _packb(method), payload.raw]
            for extra in msg[4:]:
                parts.append(_packb(extra))
            packed = b"".join(parts)
        else:
            packed = None
            nm = _NATIVE_METHODS
            if method in (nm if nm is not None else _native_methods()):
                if _NATIVE_WIRE is not None:
                    try:
                        packed = _NATIVE_WIRE.pack_frame(msg)
                        _TEL_NATIVE_PACK.inc()
                    except Exception:
                        packed = None
                if packed is None:
                    _TEL_FALLBACK_PACK.inc()
            if packed is None:
                packed = _packb(msg)
        _TEL_FRAMES_OUT[kind].inc()
        _TEL_BYTES_OUT[kind].inc(len(packed))
        return [packed]

    def _send_nowait(self, msg) -> None:
        if self._closed:
            raise ConnectionLost("connection closed")
        blob = msg[1] == _KIND_BLOB or msg[1] == _KIND_BLOB_REP
        if _send_interceptor is not None:
            if blob:
                # Materialize before offering: a dropped/delayed/duplicated
                # blob frame must be one atomic unit with a stable copy of
                # the data, not a view into live (reusable) arena memory.
                msg = [msg[0], msg[1], msg[2], msg[3], _blob_bytes(msg[4])]
            if _send_interceptor(self, msg):
                return  # consumed by fault injection (dropped/held/delayed)
        self._out.extend(self._pack_frame(msg))
        if blob:
            # Blob buffers may be live arena views the caller only pins for
            # the duration of this call: hand them to the transport NOW (an
            # unwritable socket copies them into asyncio's own buffer).
            self._flush()
        elif not self._flush_scheduled and not self._in_read:
            self._flush_scheduled = True
            self._loop.call_soon(self._flush)

    def _send_direct(self, msg) -> None:
        """Enqueue a frame bypassing the interceptor: the delivery half of a
        delayed/duplicated/reordered fault. No-op on a closed connection (a
        delay timer may outlive the link)."""
        if self._closed:
            return
        self._out.extend(self._pack_frame(msg))
        if msg[1] == _KIND_BLOB or msg[1] == _KIND_BLOB_REP:
            self._flush()
        elif not self._flush_scheduled and not self._in_read:
            self._flush_scheduled = True
            self._loop.call_soon(self._flush)

    def _flush(self) -> None:
        self._flush_scheduled = False
        if self._closed or not self._out:
            self._out.clear()
            return
        out = self._out
        self._out = []
        transport = self._protocol.transport
        if len(out) == 1:
            transport.write(out[0])
            return
        # Join adjacent small frames into one write (the control-plane hot
        # path: one syscall per loop tick); large blob memoryviews are
        # written individually so they reach the socket with no join copy.
        pending: list = []
        for item in out:
            if isinstance(item, memoryview) and item.nbytes >= _WRITE_JOIN_MAX:
                if pending:
                    transport.write(
                        pending[0] if len(pending) == 1 else b"".join(pending)
                    )
                    pending.clear()
                transport.write(item)
            else:
                pending.append(item)
        if pending:
            transport.write(pending[0] if len(pending) == 1 else b"".join(pending))

    async def drain(self) -> None:
        """Wait until the transport's write buffer is below the high-water
        mark. Bulk senders (object transfer) call this between chunks."""
        self._flush()
        if self._protocol._paused and not self._closed:
            w = self._loop.create_future()
            self._protocol._drain_waiters.append(w)
            await w
            if self._closed:
                raise ConnectionLost("connection closed")

    # -- request/reply -------------------------------------------------------

    def call_nowait(
        self, method: str, payload: Any = None, deadline: Optional[float] = None
    ) -> asyncio.Future:
        """Issue a request; returns the reply future. ``deadline`` is an
        absolute loop.time() instant carried to the server as a TTL; the
        caller still owns its own wait. Loop thread only."""
        msgid = next(self._msgid)
        fut = self._loop.create_future()
        fut.rpc_msgid = msgid
        self._pending[msgid] = fut
        frame = [msgid, _KIND_REQ, method, payload]
        tctx = _trace_ctx.get()
        if deadline is not None or tctx is not None:
            frame.append(deadline)
        if tctx is not None:
            frame.append([tctx[0], tctx[1]])
        try:
            self._send_nowait(frame)
        except ConnectionLost:
            self._pending.pop(msgid, None)
            raise
        return fut

    def call_cb(self, method: str, payload: Any, cb: Callable[[Any, Optional[str]], None]) -> None:
        """Issue a request whose reply invokes ``cb(reply, error)`` INLINE
        from the read path — no Future, no call_soon hop. The per-message
        saving (~5us) matters on >10k-msgs/s pipelines (task dispatch).
        ``cb`` runs on the loop thread and must not raise; on connection
        loss every outstanding callback fires with error='connection lost'.
        Loop thread only."""
        msgid = next(self._msgid)
        self._cb_pending[msgid] = cb
        frame = [msgid, _KIND_REQ, method, payload]
        tctx = _trace_ctx.get()
        if tctx is not None:
            frame.append(None)
            frame.append([tctx[0], tctx[1]])
        try:
            self._send_nowait(frame)
        except ConnectionLost:
            self._cb_pending.pop(msgid, None)
            raise

    def _effective_deadline(self, timeout: Optional[float]) -> Optional[float]:
        """Fold the explicit timeout with the ambient handler deadline: a
        call made while serving a deadlined request never outlives its
        caller, whatever timeout it asked for locally."""
        ambient = _ambient_deadline.get()
        local = None if timeout is None else self._loop.time() + timeout
        if ambient is None:
            return local
        if local is None:
            return ambient
        return min(ambient, local)

    async def call(self, method: str, payload: Any = None, timeout: Optional[float] = None):
        """Issue a request and await the reply. The effective budget —
        ``timeout`` clamped by the ambient handler deadline — rides the
        frame as a TTL so every downstream hop sees it shrink."""
        deadline = self._effective_deadline(timeout)
        fut = self.call_nowait(method, payload, deadline=deadline)
        return await self._await_reply(fut, deadline)

    async def _await_reply(self, fut: asyncio.Future, deadline: Optional[float]):
        """Await a reply future under an absolute deadline. The timeout is
        a loop timer (mark-then-cancel), NOT asyncio.wait_for: wait_for
        wraps every call in an extra waiter task, which at lease rates is
        the single largest source of event-loop churn — a timer costs one
        heap entry and nothing more on the (common) in-time reply."""
        if deadline is None:
            try:
                return await fut
            finally:
                if fut.cancelled():
                    self._pending.pop(fut.rpc_msgid, None)
        timer = self._loop.call_at(deadline, _cancel_for_timeout, fut)
        try:
            return await fut
        except asyncio.CancelledError:
            if getattr(fut, "rpc_timed_out", False):
                raise asyncio.TimeoutError() from None
            raise
        finally:
            timer.cancel()
            # On timeout or caller cancellation the reply will never be
            # consumed; drop the entry so the pending table doesn't leak.
            if fut.cancelled():
                self._pending.pop(fut.rpc_msgid, None)

    # -- batched lease frames ------------------------------------------------

    def call_batched_nowait(
        self, method: str, payload: Any = None, deadline: Optional[float] = None
    ) -> asyncio.Future:
        """Like ``call_nowait``, but the request coalesces with every other
        batched call issued on this connection in the same event-loop tick
        into one ``LeaseBatch`` frame (one pack + one write for N lease
        ops). Entries keep their own msgid, deadline, and trace context, so
        dedup/cancellation/chaos semantics are per-lease; the receiving
        rpc layer re-injects each entry through normal request dispatch.
        Until the flush tick runs the entry can be withdrawn with
        ``try_cancel_batched`` (a cancel for a frame that never went out
        must not reach the wire). Loop thread only."""
        if self._closed:
            raise ConnectionLost("connection closed")
        msgid = next(self._msgid)
        fut = self._loop.create_future()
        fut.rpc_msgid = msgid
        self._pending[msgid] = fut
        tctx = _trace_ctx.get()
        self._batch_entries.append(
            [msgid, method, payload, deadline,
             None if tctx is None else [tctx[0], tctx[1]]]
        )
        if not self._batch_scheduled:
            self._batch_scheduled = True
            self._loop.call_soon(self._flush_batch)
        return fut

    async def call_batched(
        self, method: str, payload: Any = None, timeout: Optional[float] = None
    ):
        """Batched counterpart of ``call``: enqueue into this tick's lease
        batch and await the per-entry reply."""
        deadline = self._effective_deadline(timeout)
        fut = self.call_batched_nowait(method, payload, deadline=deadline)
        return await self._await_reply(fut, deadline)

    def try_cancel_batched(self, msgid: int) -> bool:
        """Withdraw a batched request that has NOT been flushed yet.
        Returns True when the entry was still queued locally: it is removed
        from the pending batch and its future is cancelled, and the caller
        must NOT send a wire cancel (the request never existed remotely).
        False means the batch already went out — cancel over the wire as
        usual. Loop thread only."""
        entries = self._batch_entries
        for i, entry in enumerate(entries):
            if entry[0] == msgid:
                del entries[i]
                fut = self._pending.pop(msgid, None)
                if fut is not None and not fut.done():
                    fut.cancel()
                return True
        return False

    def _flush_batch(self) -> None:
        self._batch_scheduled = False
        entries = self._batch_entries
        if not entries or self._closed:
            # Everything was withdrawn pre-flush, or the link died
            # (teardown already failed the pending futures).
            return
        self._batch_entries = []
        _TEL_BATCH_SIZE.observe(len(entries))
        try:
            if len(entries) == 1:
                # Singleton: a plain request frame is cheaper than a
                # 1-entry batch and semantically identical.
                mid, method, payload, deadline, tctx = entries[0]
                frame = [mid, _KIND_REQ, method, payload]
                if deadline is not None or tctx is not None:
                    frame.append(deadline)
                if tctx is not None:
                    frame.append(tctx)
                self._send_nowait(frame)
            else:
                self._send_nowait(
                    [0, _KIND_PUSH, "LeaseBatch", {"entries": entries}]
                )
        except ConnectionLost:
            pass  # teardown already failed every pending future

    @property
    def write_paused(self) -> bool:
        """True while the transport has backpressured writes (high-water
        mark hit). Broadcast fan-out uses this to decide between an inline
        write and a backpressure-aware drain task."""
        return self._protocol._paused

    def push_nowait(self, method: str, payload: Any = None) -> None:
        """One-way message; no reply expected. Loop thread only."""
        self._send_nowait([0, _KIND_PUSH, method, payload])

    def push_packed_nowait(self, packed: bytes) -> None:
        """Write a frame pre-packed by ``pack_push`` — the broadcast fan-out
        hot path: the publisher packs once and hands every subscriber the
        same bytes instead of paying one msgpack encode per subscriber.
        Loop thread only."""
        if self._closed:
            raise ConnectionLost("connection closed")
        _TEL_FRAMES_OUT[_KIND_PUSH].inc()
        _TEL_BYTES_OUT[_KIND_PUSH].inc(len(packed))
        self._out.append(packed)
        if not self._flush_scheduled and not self._in_read:
            self._flush_scheduled = True
            self._loop.call_soon(self._flush)

    def push_packed_now(self, packed: bytes) -> None:
        """``push_packed_nowait`` + immediate transport write. Broadcast
        fan-out sends exactly one frame per subscriber per round — there is
        nothing to coalesce, so the per-connection flush callback is pure
        overhead (N loop callbacks per round at N subscribers)."""
        if self._closed:
            raise ConnectionLost("connection closed")
        _TEL_FRAMES_OUT[_KIND_PUSH].inc()
        _TEL_BYTES_OUT[_KIND_PUSH].inc(len(packed))
        self._out.append(packed)
        self._flush()

    async def push(self, method: str, payload: Any = None) -> None:
        self._send_nowait([0, _KIND_PUSH, method, payload])

    # -- blob sidecar frames -------------------------------------------------

    def blob_push_nowait(self, method: str, payload: Any, blob) -> None:
        """One-way blob frame: msgpack control message + raw sidecar bytes.
        ``blob`` is bytes/memoryview or a list of them; the buffers are
        handed to the transport before this returns (scatter-gather, no pack
        copy), so live arena views are safe to pass. Loop thread only."""
        self._send_nowait([0, _KIND_BLOB, method, payload, blob])

    async def call_with_blob(
        self, method: str, payload: Any, blob, timeout: Optional[float] = None
    ):
        """Issue a request whose bulk data rides as a blob sidecar instead
        of inside the msgpack payload; awaits the reply like ``call``. The
        receiver's sink factory (or the default buffer, delivered to the
        handler as ``payload["data"]``) consumes the bytes."""
        msgid = next(self._msgid)
        fut = self._loop.create_future()
        fut.rpc_msgid = msgid
        self._pending[msgid] = fut
        try:
            self._send_nowait([msgid, _KIND_BLOB, method, payload, blob])
        except ConnectionLost:
            self._pending.pop(msgid, None)
            raise
        try:
            if timeout is None:
                return await fut
            return await asyncio.wait_for(fut, timeout)
        finally:
            if fut.cancelled():
                self._pending.pop(msgid, None)

    async def call_into(
        self, method: str, payload: Any, sink, timeout: Optional[float] = None
    ):
        """Issue a request whose reply may carry a blob sidecar streamed
        into ``sink`` (``write(view)`` per chunk, ``done(ok)`` at the end).
        Returns the reply's meta payload once the blob has fully landed.
        An error reply or a plain reply resolves without touching the
        sink."""
        deadline = self._effective_deadline(timeout)
        fut = self.call_nowait(method, payload, deadline=deadline)
        msgid = fut.rpc_msgid
        self._blob_reply_sinks[msgid] = sink
        try:
            if deadline is None:
                return await fut
            return await asyncio.wait_for(
                fut, max(0.0, deadline - self._loop.time())
            )
        finally:
            self._blob_reply_sinks.pop(msgid, None)
            if fut.cancelled():
                self._pending.pop(msgid, None)

    # -- read path -----------------------------------------------------------

    def reply_nowait(self, msgid: int, method: str, payload: Any) -> None:
        """Send a reply for a request handled by a sync handler."""
        try:
            self._send_nowait([msgid, _KIND_REP, method, payload])
        except ConnectionLost:
            pass

    def reply_error_nowait(self, msgid: int, method: str, err: str) -> None:
        try:
            self._send_nowait([msgid, _KIND_ERR, method, err])
        except ConnectionLost:
            pass

    def _select_blob_sink(self, msg: list, size: int):
        """Pick the sink for an inbound blob; returns (sink, external).
        ``external`` sinks (factory- or call_into-registered) own delivery;
        the default BufferSink's contents are instead injected into the
        payload as ``data`` and dispatched like a normal message."""
        msgid, kind, method, payload = msg[0], msg[1], msg[2], msg[3]
        if kind == _KIND_BLOB_REP:
            sink = self._blob_reply_sinks.pop(msgid, None)
            if sink is not None:
                return sink, True
            return BufferSink(size), False
        factory = self._blob_factories.get(method)
        if factory is not None:
            try:
                sink = factory(self, payload, size)
            except Exception:
                logger.exception("blob sink factory for %s failed", method)
                sink = None
            if sink is not None:
                return sink, True
            return _NullSink(), True  # declined: drain and discard
        return BufferSink(size), False

    def _on_blob_complete(self, msg: list, sink, external: bool) -> None:
        """A blob fully landed: finish the sink, then deliver the control
        message (resolve the pending call for a blob reply; dispatch the
        handler for a blob push/request)."""
        msgid, kind, method, payload = msg[0], msg[1], msg[2], msg[3]
        try:
            sink.done(True)
        except Exception:
            logger.exception("blob sink completion for %s failed", method)
        if kind == _KIND_BLOB_REP:
            if not external and isinstance(payload, dict):
                payload["data"] = sink.value()
            cb = self._cb_pending.pop(msgid, None)
            if cb is not None:
                try:
                    cb(payload, None)
                except Exception:
                    logger.exception("inline reply callback failed")
                return
            fut = self._pending.pop(msgid, None)
            if fut is not None and not fut.done():
                fut.set_result(payload)
            return
        if external:
            # The sink consumed the data plane; only a request (msgid != 0)
            # still needs its handler to produce a reply.
            if msgid:
                spawn(self._dispatch(msgid, method, payload))
            return
        if isinstance(payload, dict):
            payload["data"] = sink.value()
        spawn(self._dispatch(msgid or None, method, payload))

    def _on_message(self, msg) -> None:
        msgid, kind, method, payload = msg[0], msg[1], msg[2], msg[3]
        _TEL_FRAMES_IN[kind].inc()
        if kind == _KIND_REQ:
            deadline = None
            if len(msg) > 4 and msg[4] is not None:
                ttl = msg[4]
                if ttl <= 0:
                    # Shed stale work: the caller has already given up.
                    deadline_stats.shed += 1
                    _TEL_DL_SHED.inc()
                    telemetry.record_event(
                        "rpc", "deadline_shed", method=method, late_s=-ttl
                    )
                    self.reply_error_nowait(
                        msgid,
                        method,
                        f"DeadlineExceeded: {method} arrived "
                        f"{-ttl:.3f}s past its deadline (shed)",
                    )
                    return
                deadline = self._loop.time() + ttl
            tctx = None
            if len(msg) > 5 and msg[5] is not None:
                tctx = (msg[5][0], msg[5][1])
            sync_h = self._sync_handlers.get(method)
            if sync_h is not None:
                # Set the ambient deadline (and trace context) around the
                # inline handler so any coroutine it spawn()s inherits both.
                token = _ambient_deadline.set(deadline)
                ttoken = _trace_ctx.set(tctx)
                try:
                    sync_h(self, msgid, payload)
                except Exception as e:
                    self.reply_error_nowait(
                        msgid, method, f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
                    )
                finally:
                    _trace_ctx.reset(ttoken)
                    _ambient_deadline.reset(token)
                return
            spawn(self._dispatch(msgid, method, payload, deadline, tctx))
        elif kind == _KIND_PUSH:
            if method == "LeaseBatch":
                # Unbundle: re-inject every entry as its own request frame
                # through this same dispatch path, so per-entry TTL shed,
                # sync fast-path handlers, dedup ledgers, and trace context
                # all behave exactly as for unbatched frames. The N replies
                # coalesce back into one write on the next flush tick.
                for e in payload["entries"]:
                    self._on_message([e[0], _KIND_REQ, e[1], e[2], e[3], e[4]])
                return
            sync_h = self._sync_handlers.get(method)
            if sync_h is not None:
                # Push fast path: no task per broadcast delivery. The
                # handler gets msgid=None (pushes have no reply).
                try:
                    sync_h(self, None, payload)
                except Exception:
                    logger.exception("sync push handler %s failed", method)
                return
            spawn(self._dispatch(None, method, payload))
        else:
            cb = self._cb_pending.pop(msgid, None)
            if cb is not None:
                try:
                    if kind == _KIND_REP:
                        cb(payload, None)
                    else:
                        cb(None, payload)
                except Exception:
                    logger.exception("inline reply callback failed")
                return
            fut = self._pending.pop(msgid, None)
            if fut is not None and not fut.done():
                if kind == _KIND_REP:
                    fut.set_result(payload)
                else:
                    fut.set_exception(_typed_error(payload))

    async def _dispatch(
        self,
        msgid,
        method: str,
        payload,
        deadline: Optional[float] = None,
        trace_ctx: Optional[tuple] = None,
    ) -> None:
        handler = self._handlers.get(method)
        # Each dispatch runs in its own task (own context copy), so setting
        # the ambient deadline (and trace context) here scopes them to this
        # handler and every call it makes downstream.
        _ambient_deadline.set(deadline)
        _trace_ctx.set(trace_ctx)
        obs = self._dispatch_observer
        t0 = self._loop.time() if obs is not None else 0.0
        try:
            if handler is None:
                raise RpcError(f"no handler for method {method!r}")
            if deadline is None:
                result = await handler(self, payload)
            else:
                result = await self._run_deadlined(handler, method, payload, deadline)
        except Exception as e:
            if obs is not None:
                obs(method, self._loop.time() - t0)
            # Any handler failure — including ConnectionLost from a dial the
            # handler made to a third party — must produce an error reply, or
            # the caller waits out its full timeout.
            if msgid is not None:
                err = f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
                try:
                    self._send_nowait([msgid, _KIND_ERR, method, err])
                except ConnectionLost:
                    pass  # our own link died; caller learns via teardown
            else:
                logger.exception("push handler %s failed", method)
            return
        if obs is not None:
            obs(method, self._loop.time() - t0)
        if msgid is not None:
            try:
                if isinstance(result, Blob):
                    # Blob reply: no awaits between the handler returning its
                    # (possibly arena-backed) views and the transport write
                    # inside _send_nowait, so the span cannot be recycled
                    # under the send.
                    self._send_nowait(
                        [msgid, _KIND_BLOB_REP, method, result.payload, result.blob]
                    )
                else:
                    self._send_nowait([msgid, _KIND_REP, method, result])
            except ConnectionLost:
                pass

    async def _run_deadlined(self, handler, method: str, payload, deadline: float):
        """Run a handler under its wire deadline: shed if already expired,
        cancel at the deadline (the caller gave up at the same instant, so
        the result would be discarded anyway), and record handlers whose
        finish — or cancellation unwind — runs more than the grace period
        late (the no-call-outlives-deadline invariant's raw data)."""
        remaining = deadline - self._loop.time()
        if remaining <= 0:
            deadline_stats.shed += 1
            _TEL_DL_SHED.inc()
            telemetry.record_event(
                "rpc", "deadline_shed", method=method, late_s=-remaining
            )
            raise DeadlineExceeded(
                f"{method} shed before dispatch: deadline expired "
                f"{-remaining:.3f}s ago"
            )
        try:
            result = await asyncio.wait_for(handler(self, payload), remaining)
        except asyncio.TimeoutError:
            deadline_stats.enforced += 1
            _TEL_DL_ENFORCED.inc()
            telemetry.record_event(
                "rpc", "deadline_enforced", method=method, budget_s=remaining
            )
            raise DeadlineExceeded(
                f"{method} handler cancelled at its deadline "
                f"({remaining:.3f}s budget on arrival)"
            ) from None
        finally:
            late = self._loop.time() - deadline
            if late > config.rpc_deadline_grace_s:
                deadline_stats.overruns.append((method, late))
                _TEL_DL_OVERRUNS.inc()
                telemetry.record_event(
                    "rpc", "deadline_overrun", method=method, late_s=late
                )
            elif late <= 0:
                deadline_stats.met += 1
                _TEL_DL_MET.inc()
        return result

    # -- lifecycle -----------------------------------------------------------

    def _teardown(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._out.clear()
        self._batch_entries.clear()
        # Fail the mid-stream blob (the sink may hold a partially-written
        # arena span: done(False) lets it abort/quarantine) and any sinks
        # still waiting for a blob reply.
        proto = self._protocol
        sink = proto._blob_sink
        if sink is not None:
            proto._blob_sink = None
            proto._blob_msg = None
            proto._blob_remaining = 0
            try:
                sink.done(False)
            except Exception:
                logger.exception("blob sink teardown failed")
        if self._blob_reply_sinks:
            sinks, self._blob_reply_sinks = self._blob_reply_sinks, {}
            for s in sinks.values():
                try:
                    s.done(False)
                except Exception:
                    logger.exception("blob reply sink teardown failed")
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionLost("connection closed"))
        self._pending.clear()
        if self._cb_pending:
            cbs, self._cb_pending = self._cb_pending, {}
            for cb in cbs.values():
                try:
                    cb(None, _CONNECTION_LOST)
                except Exception:
                    logger.exception("inline reply callback failed at teardown")
        try:
            if self._protocol.transport is not None:
                self._protocol.transport.close()
        except Exception:
            pass
        if self._on_close is not None:
            try:
                self._on_close(self)
            except Exception:
                logger.exception("on_close callback failed")

    async def close(self) -> None:
        self._teardown()

    @property
    def closed(self) -> bool:
        return self._closed


class Server:
    """RPC server: accepts connections, dispatches to registered handlers.

    Handlers are ``async def handler(conn, payload) -> reply``.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._port = port
        self._handlers: Dict[str, Callable] = {}
        self._sync_handlers: Dict[str, Callable] = {}
        self._blob_factories: Dict[str, Callable] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: set = set()
        self._on_disconnect: Optional[Callable[[Connection], None]] = None
        # Per-dispatch ``(method, seconds)`` hook, copied onto every
        # accepted connection (service-latency telemetry; see Connection).
        self.dispatch_observer: Optional[Callable[[str, float], None]] = None

    def handler(self, name: str):
        def deco(fn):
            self._handlers[name] = fn
            return fn

        return deco

    def register(self, name: str, fn: Callable) -> None:
        self._handlers[name] = fn

    def register_sync(self, name: str, fn: Callable) -> None:
        """Register a sync fast-path handler ``fn(conn, msgid, payload)``."""
        self._sync_handlers[name] = fn

    def register_blob(self, name: str, factory: Callable) -> None:
        """Register a blob sink factory ``factory(conn, payload, size) ->
        sink | None`` for inbound kind-4 frames of this method. The factory
        runs inline from the read path; returning None drains and discards
        the blob. The sink's ``write(view)`` is called per streamed chunk
        (the view is transient — copy it) and ``done(ok)`` once on full
        arrival (ok=True) or connection teardown (ok=False)."""
        self._blob_factories[name] = factory

    def on_disconnect(self, fn: Callable[[Connection], None]) -> None:
        self._on_disconnect = fn

    def _make_protocol(self) -> _RpcProtocol:
        conn = Connection(
            self._handlers,
            on_close=self._conn_closed,
            sync_handlers=self._sync_handlers,
            blob_factories=self._blob_factories,
            dispatch_observer=self.dispatch_observer,
        )
        self.connections.add(conn)
        return conn._protocol

    async def start(self) -> Tuple[str, int]:
        loop = asyncio.get_running_loop()
        self._server = await loop.create_server(
            self._make_protocol, self._host, self._port
        )
        sock = self._server.sockets[0]
        self._host, self._port = sock.getsockname()[:2]
        # Same-host peers dial the Unix socket instead of TCP loopback
        # (~40% less kernel CPU per frame on the chatty control plane); the
        # path is derived from the TCP port, so the advertised (host, port)
        # address stays the only address anyone needs to know.
        try:
            path = _uds_path(self._port)
            if os.path.exists(path):
                os.unlink(path)
            self._uds_server = await loop.create_unix_server(self._make_protocol, path)
            self._uds_path = path
        except Exception:  # pragma: no cover - platform without UDS
            self._uds_server = None
            self._uds_path = None
        return self._host, self._port

    @property
    def address(self) -> Tuple[str, int]:
        return self._host, self._port

    def _conn_closed(self, conn: Connection) -> None:
        self.connections.discard(conn)
        if self._on_disconnect is not None:
            self._on_disconnect(conn)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
        if getattr(self, "_uds_server", None) is not None:
            self._uds_server.close()
            try:
                os.unlink(self._uds_path)
            except OSError:
                pass
        # Close live connections before wait_closed(): since py3.12.1
        # wait_closed blocks until every client transport is gone.
        for conn in list(self.connections):
            await conn.close()
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=5)
            except asyncio.TimeoutError:
                pass


async def connect(
    host: str,
    port: int,
    handlers: Optional[Dict[str, Callable]] = None,
    retry: Optional[int] = None,
    retry_interval: Optional[float] = None,
    sync_handlers: Optional[Dict[str, Callable]] = None,
    policy: Optional[RetryPolicy] = None,
    blob_factories: Optional[Dict[str, Callable]] = None,
) -> Connection:
    """Dial a server, retrying with jittered exponential backoff while it
    boots. Returns a duplex Connection.

    By default the dial schedule comes from :meth:`RetryPolicy.for_dial`
    (config knobs ``rpc_dial_*``). Legacy ``retry``/``retry_interval``
    arguments are mapped onto an equivalent policy — ``retry`` caps the
    attempt count and ``retry * retry_interval`` caps the total wait — so
    existing call sites keep their expected patience.
    """
    loop = asyncio.get_running_loop()
    if policy is None:
        if retry is None and retry_interval is None:
            policy = RetryPolicy.for_dial()
        else:
            n = 30 if retry is None else max(1, retry)
            interval = 0.1 if retry_interval is None else retry_interval
            policy = RetryPolicy(
                initial_backoff_s=interval,
                max_backoff_s=interval * 8,
                multiplier=config.rpc_backoff_multiplier,
                max_attempts=n,
                total_budget_s=n * interval,
            )
    last_err: Optional[Exception] = None
    uds = _uds_path(port) if host in _LOOPBACK else None
    backoffs = policy.backoffs()
    start = loop.time()
    attempt = 0
    while True:
        attempt += 1
        try:
            # NB: keep the caller's dict object (even if currently empty) so
            # handlers registered later are visible on this connection.
            conn = Connection(
                handlers if handlers is not None else {},
                sync_handlers=sync_handlers,
                blob_factories=blob_factories,
            )
            conn.remote_addr = (host, port)
            if uds is not None and os.path.exists(uds):
                try:
                    await loop.create_unix_connection(lambda: conn._protocol, uds)
                    return conn
                except (ConnectionRefusedError, OSError):
                    pass  # stale socket file; fall through to TCP
            await loop.create_connection(lambda: conn._protocol, host, port)
            return conn
        except (ConnectionRefusedError, OSError) as e:
            last_err = e
        delay = next(backoffs)
        if not policy.allows(attempt + 1, (loop.time() - start) + delay):
            break
        await asyncio.sleep(delay)
    raise ConnectionLost(
        f"could not connect to {host}:{port} "
        f"after {attempt} attempts: {last_err}"
    )


class RetryableConnection:
    """A Connection wrapper that survives the link: transparent re-dial on
    ``ConnectionLost``/timeout, with in-flight calls queued during the
    reconnect window and drained against the fresh link — the reference
    runtime's retryable gRPC client (``retryable_grpc_client.h``, and the
    GCS client's failover call queue) in miniature.

    Retry *safety* is per method, declared in ``wire.SCHEMAS``:

    - ``"safe"`` — idempotent; retried freely.
    - ``"dedup"`` — retried only when the payload carries the schema's
      msgid-stable dedup token (e.g. ``lease_id``), which the server uses
      to mirror the original outcome instead of re-applying.
    - ``"none"`` — never retried; the first failure surfaces.

    Methods missing from the registry use ``default_retry`` (constructor
    argument; "safe" fits channels whose handlers are keyed upserts/reads
    by construction, like the GCS control plane).

    The wrapper owns reconnection, not call-level deadlines: each attempt
    inherits the caller's ``timeout`` folded with the ambient handler
    deadline, and the overall retry loop gives up when that budget — or the
    policy's — runs out.

    ``resolver`` makes re-dial target-aware: an async callable returning
    the *current* ``(host, port)`` of the service (or None to keep the last
    known address). When set, every reconnect re-resolves before dialing
    and the address is passed to ``dial(addr)`` — how clients follow a GCS
    leader across failover instead of hammering the dead primary.
    """

    def __init__(
        self,
        dial: Callable[[], Awaitable[Connection]],
        conn: Optional[Connection] = None,
        policy: Optional[RetryPolicy] = None,
        default_retry: str = "none",
        attempt_timeout_s: Optional[float] = None,
        on_reconnect: Optional[Callable[[Connection], Awaitable[None]]] = None,
        name: str = "rpc",
        rng: Optional[random.Random] = None,
        resolver: Optional[
            Callable[[], Awaitable[Optional[Tuple[str, int]]]]
        ] = None,
    ):
        self._dial = dial
        self._resolver = resolver
        self.conn = conn
        self._policy = policy or RetryPolicy.for_calls()
        self._default_retry = default_retry
        # Per-attempt cap so a request whose reply was dropped doesn't pin
        # the whole budget. 0/None disables it (required for channels that
        # carry long-polls, e.g. CreateActor wait_alive).
        if attempt_timeout_s is None:
            attempt_timeout_s = config.rpc_default_timeout_s
        self._attempt_timeout_s = attempt_timeout_s or None
        self._on_reconnect = on_reconnect
        self._name = name
        self._rng = rng or random.Random()
        self._lock: Optional[asyncio.Lock] = None  # lazy: loop-bound
        self._closed = False
        # Legacy per-channel dict kept for direct readers (tests, repr);
        # the cluster-visible copies are the telemetry cells below.
        self.stats = {"redials": 0, "retries": 0, "queued": 0}  # telemetry: allow-adhoc-stats
        self._tel_redials = telemetry.counter(
            "rpc", "redials", "reconnects of a retryable channel"
        ).cell(channel=name)
        self._tel_retries = telemetry.counter(
            "rpc", "retries", "calls transparently re-issued after a failure"
        ).cell(channel=name)
        self._tel_queued = telemetry.counter(
            "rpc", "retry_queued", "calls that waited out a reconnect"
        ).cell(channel=name)

    @property
    def closed(self) -> bool:
        return self._closed

    def _retry_mode(self, method: str, payload: Any) -> str:
        """"safe" if this (method, payload) may be re-sent, else "none"."""
        from ray_tpu._private import wire  # lazy: avoid import cycle

        mode, dedup_key = wire.retry_class(method, self._default_retry)
        if mode == wire.RETRY_DEDUP:
            token = payload.get(dedup_key) if isinstance(payload, dict) else None
            return wire.RETRY_SAFE if token is not None else wire.RETRY_NONE
        return mode

    async def _ensure_connected(self) -> Connection:
        """Current live connection, (re)dialing under a lock if needed.
        Sets ``self.conn`` *before* firing ``on_reconnect`` so re-entrant
        calls made from the callback hit the fast path instead of
        deadlocking on the lock."""
        conn = self.conn
        if conn is not None and not conn.closed:
            return conn
        if self._closed:
            raise ConnectionLost(f"{self._name}: client closed")
        if self._lock is None:
            self._lock = asyncio.Lock()
        queued = self._lock.locked()
        if queued:
            self.stats["queued"] += 1
            self._tel_queued.inc()
        async with self._lock:
            conn = self.conn
            if conn is not None and not conn.closed:
                return conn  # another waiter already reconnected
            if self._closed:
                raise ConnectionLost(f"{self._name}: client closed")
            if self._resolver is not None:
                addr = None
                try:
                    addr = await self._resolver()
                except Exception:
                    logger.debug("%s: address resolver failed; using last "
                                 "known address", self._name, exc_info=True)
                conn = await self._dial(addr)
            else:
                conn = await self._dial()
            self.conn = conn
            self.stats["redials"] += 1
            self._tel_redials.inc()
            telemetry.record_event("rpc", "redial", channel=self._name)
            if self._on_reconnect is not None:
                await self._on_reconnect(conn)
            return conn

    async def call(
        self, method: str, payload: Any = None, timeout: Optional[float] = None
    ):
        """Issue a request, retrying per the method's wire retry class.

        The overall budget is ``timeout`` folded with the ambient handler
        deadline and the policy's total budget; backoffs are clamped to it.
        Non-retryable failures — and retryable ones once the budget is
        spent — propagate to the caller.
        """
        loop = asyncio.get_running_loop()
        ambient = _ambient_deadline.get()
        overall: Optional[float] = None
        if timeout is not None:
            overall = loop.time() + timeout
        if ambient is not None:
            overall = ambient if overall is None else min(overall, ambient)
        start = loop.time()
        backoffs = self._policy.backoffs(self._rng)
        attempt = 0
        while True:
            attempt += 1
            try:
                conn = await self._ensure_connected()
                attempt_timeout = self._attempt_timeout_s
                if overall is not None:
                    remaining = overall - loop.time()
                    if remaining <= 0:
                        raise DeadlineExceeded(
                            f"{self._name}: {method} budget exhausted "
                            f"before attempt {attempt}"
                        )
                    if attempt_timeout is None or attempt_timeout > remaining:
                        attempt_timeout = remaining
                return await conn.call(method, payload, timeout=attempt_timeout)
            except (ConnectionLost, asyncio.TimeoutError, StaleLeaderError) as e:
                if isinstance(e, StaleLeaderError):
                    # The peer lost leadership: the write was rejected, not
                    # applied. Drop the link so the next attempt re-resolves
                    # (and re-dials) the current leader. Without a resolver
                    # this still lands on the restarted/promoted address.
                    if self.conn is conn and not conn.closed:
                        self.conn = None
                        spawn(conn.close())
                if self._closed:
                    raise
                if self._retry_mode(method, payload) != "safe":
                    raise
                delay = next(backoffs)
                now = loop.time()
                if not self._policy.allows(attempt + 1, (now - start) + delay):
                    raise
                if overall is not None:
                    remaining = overall - now
                    if remaining <= delay:
                        raise
                self.stats["retries"] += 1
                self._tel_retries.inc()
                telemetry.record_event(
                    "rpc", "retry", channel=self._name, method=method
                )
                logger.debug(
                    "%s: retrying %s after %s (attempt %d, sleeping %.3fs)",
                    self._name, method, type(e).__name__, attempt, delay,
                )
                await asyncio.sleep(delay)

    async def close(self) -> None:
        """Terminal: no further re-dials; in-flight retry loops surface
        their pending error instead of reconnecting."""
        self._closed = True
        if self.conn is not None:
            await self.conn.close()
