"""Asyncio RPC layer: streaming msgpack frames over TCP.

TPU-native analog of the reference's rpc scaffolding (src/ray/rpc/): persistent
client connections with call multiplexing, a handler-registry server, and
server->client push for pubsub channels. The reference wraps gRPC; we use a
lean custom framing because every daemon here is an asyncio program and the
control-plane messages are small dicts — msgpack round-trips them with no
codegen step. Payloads that carry Python objects (task args, actor state)
are cloudpickled into opaque ``bytes`` fields by the caller.

Wire format: a raw msgpack stream; each message is ``[msgid, kind, method,
payload]``. Kinds: 0=request, 1=reply, 2=error-reply, 3=push (one-way).
msgpack is self-framing, so no length prefix is needed — the receiving side
feeds whole socket chunks to a streaming Unpacker and drains every complete
message per chunk with zero per-frame awaits.

Throughput design (reference: the C++ layer's batched stream writes in
ClientCallManager): the hot path is callback-based, not coroutine-based.
``call_nowait`` appends a pre-packed frame to a per-connection out-buffer and
schedules ONE flush per event-loop tick (``call_soon``), collapsing any number
of pipelined requests into a single ``transport.write`` syscall; replies are
dispatched inline from ``data_received``. ``call``/``push`` remain the
coroutine conveniences on top.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import tempfile
import traceback
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

import msgpack


def _uds_path(port: int) -> str:
    return os.path.join(tempfile.gettempdir(), f"ray_tpu_uds_{port}.sock")


_LOOPBACK = frozenset({"127.0.0.1", "localhost", "::1"})

logger = logging.getLogger(__name__)

# The event loop holds only weak references to tasks: a fire-and-forget
# asyncio.create_task() whose result is dropped can be garbage-collected
# mid-flight (observed as lease requests silently vanishing under GC
# pressure). Every background task in the runtime goes through spawn(),
# which parks a strong reference until the task completes.
_BG_TASKS: set = set()


def spawn(coro) -> asyncio.Task:
    # The one sanctioned create_task call site: spawn() IS the wrapper the
    # raw-create-task rule points everyone at.
    task = asyncio.get_running_loop().create_task(coro)  # aio-lint: disable=raw-create-task
    _BG_TASKS.add(task)
    task.add_done_callback(_BG_TASKS.discard)
    return task


_KIND_REQ = 0
_KIND_REP = 1
_KIND_ERR = 2
_KIND_PUSH = 3

_MAX_FRAME = 1 << 31

# Fault-injection hook (ray_tpu.chaos): when set, every outbound frame from
# this process is offered to the interceptor BEFORE packing. The interceptor
# returns True to consume the frame (drop it, or re-deliver it later /
# duplicated / reordered via ``Connection._send_direct``) and False to let it
# flow normally. One module-global — not per-Connection — so a chaos schedule
# covers every link in the process (GCS, raylets, driver core) without the
# daemons knowing chaos exists. None (the default) costs one global read per
# frame on the hot path. Loop thread only, like every send.
_send_interceptor: Optional[Callable[["Connection", list], bool]] = None


def set_send_interceptor(fn: Optional[Callable[["Connection", list], bool]]) -> None:
    """Install (or clear, with None) the process-wide outbound-frame
    interceptor. Test/chaos tooling only; never used in production paths."""
    global _send_interceptor
    _send_interceptor = fn


def get_send_interceptor() -> Optional[Callable[["Connection", list], bool]]:
    return _send_interceptor


# Sentinel error string delivered to call_cb callbacks on connection loss
# (distinguishes transport death from a handler-level error reply).
_CONNECTION_LOST = "__connection_lost__"


class RpcError(Exception):
    """Raised on the caller when the remote handler raised or the link died."""


class ConnectionLost(RpcError):
    pass


_packb = msgpack.Packer(use_bin_type=True, autoreset=True).pack


class _RpcProtocol(asyncio.Protocol):
    """Transport glue: buffers writes per loop tick, streams reads through a
    msgpack Unpacker, and forwards complete messages to the Connection."""

    def __init__(self, conn: "Connection"):
        self._conn = conn
        self._unpacker = msgpack.Unpacker(
            raw=False, strict_map_key=False, max_buffer_size=_MAX_FRAME
        )
        self.transport: Optional[asyncio.Transport] = None
        self._paused = False
        self._drain_waiters: list = []

    def connection_made(self, transport) -> None:
        self.transport = transport

    def connection_lost(self, exc) -> None:
        for w in self._drain_waiters:
            if not w.done():
                w.set_result(None)
        self._drain_waiters.clear()
        self._conn._teardown()

    def pause_writing(self) -> None:
        self._paused = True

    def resume_writing(self) -> None:
        self._paused = False
        for w in self._drain_waiters:
            if not w.done():
                w.set_result(None)
        self._drain_waiters.clear()

    def data_received(self, data: bytes) -> None:
        self._unpacker.feed(data)
        on_message = self._conn._on_message
        try:
            for msg in self._unpacker:
                on_message(msg)
        except Exception:
            logger.exception("rpc stream corrupted; dropping connection")
            if self.transport is not None:
                self.transport.close()


class Connection:
    """One end of a duplex RPC link. Both sides can issue requests and pushes."""

    def __init__(
        self,
        handlers: Dict[str, Callable[..., Awaitable[Any]]],
        on_close: Optional[Callable[["Connection"], None]] = None,
        sync_handlers: Optional[Dict[str, Callable]] = None,
    ):
        self._handlers = handlers
        # Sync fast-path handlers: ``fn(conn, msgid, payload)`` invoked inline
        # from data_received — no asyncio task per message. The handler must
        # not block; it replies later via ``reply_nowait``. Used for the task
        # execution hot path (reference analog: the C++ server's inlined
        # HandleRequest dispatch before posting to the io_context).
        self._sync_handlers = sync_handlers if sync_handlers is not None else {}
        self._on_close = on_close
        self._msgid = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        # Inline reply callbacks (call_cb): msgid -> cb(reply, error).
        self._cb_pending: Dict[int, Callable] = {}
        self._closed = False
        self._loop = asyncio.get_running_loop()
        self._protocol = _RpcProtocol(self)
        self._out: list = []
        self._flush_scheduled = False
        # Arbitrary per-connection state daemons can attach (e.g. worker id).
        self.context: Dict[str, Any] = {}
        # The logical (host, port) this connection was dialed to; set by
        # connect(). Stays meaningful when the transport is a Unix socket.
        self.remote_addr: Optional[Tuple[str, int]] = None

    @property
    def peername(self) -> Optional[Tuple[str, int]]:
        if self.remote_addr is not None:
            return self.remote_addr
        try:
            name = self._protocol.transport.get_extra_info("peername")
        except Exception:
            return None
        if isinstance(name, tuple) and len(name) >= 2:
            return (name[0], name[1])
        return None

    # -- write path ----------------------------------------------------------

    def _send_nowait(self, msg) -> None:
        if self._closed:
            raise ConnectionLost("connection closed")
        if _send_interceptor is not None and _send_interceptor(self, msg):
            return  # consumed by fault injection (dropped/held/delayed)
        self._out.append(_packb(msg))
        if not self._flush_scheduled:
            self._flush_scheduled = True
            self._loop.call_soon(self._flush)

    def _send_direct(self, msg) -> None:
        """Enqueue a frame bypassing the interceptor: the delivery half of a
        delayed/duplicated/reordered fault. No-op on a closed connection (a
        delay timer may outlive the link)."""
        if self._closed:
            return
        self._out.append(_packb(msg))
        if not self._flush_scheduled:
            self._flush_scheduled = True
            self._loop.call_soon(self._flush)

    def _flush(self) -> None:
        self._flush_scheduled = False
        if self._closed or not self._out:
            self._out.clear()
            return
        if len(self._out) == 1:
            data = self._out[0]
        else:
            data = b"".join(self._out)
        self._out.clear()
        self._protocol.transport.write(data)

    async def drain(self) -> None:
        """Wait until the transport's write buffer is below the high-water
        mark. Bulk senders (object transfer) call this between chunks."""
        self._flush()
        if self._protocol._paused and not self._closed:
            w = self._loop.create_future()
            self._protocol._drain_waiters.append(w)
            await w
            if self._closed:
                raise ConnectionLost("connection closed")

    # -- request/reply -------------------------------------------------------

    def call_nowait(self, method: str, payload: Any = None) -> asyncio.Future:
        """Issue a request; returns the reply future. Loop thread only."""
        msgid = next(self._msgid)
        fut = self._loop.create_future()
        fut.rpc_msgid = msgid
        self._pending[msgid] = fut
        try:
            self._send_nowait([msgid, _KIND_REQ, method, payload])
        except ConnectionLost:
            self._pending.pop(msgid, None)
            raise
        return fut

    def call_cb(self, method: str, payload: Any, cb: Callable[[Any, Optional[str]], None]) -> None:
        """Issue a request whose reply invokes ``cb(reply, error)`` INLINE
        from the read path — no Future, no call_soon hop. The per-message
        saving (~5us) matters on >10k-msgs/s pipelines (task dispatch).
        ``cb`` runs on the loop thread and must not raise; on connection
        loss every outstanding callback fires with error='connection lost'.
        Loop thread only."""
        msgid = next(self._msgid)
        self._cb_pending[msgid] = cb
        try:
            self._send_nowait([msgid, _KIND_REQ, method, payload])
        except ConnectionLost:
            self._cb_pending.pop(msgid, None)
            raise

    async def call(self, method: str, payload: Any = None, timeout: Optional[float] = None):
        """Issue a request and await the reply."""
        fut = self.call_nowait(method, payload)
        try:
            if timeout is None:
                return await fut
            return await asyncio.wait_for(fut, timeout)
        finally:
            # On timeout or caller cancellation the reply will never be
            # consumed; drop the entry so the pending table doesn't leak.
            if fut.cancelled():
                self._pending.pop(fut.rpc_msgid, None)

    def push_nowait(self, method: str, payload: Any = None) -> None:
        """One-way message; no reply expected. Loop thread only."""
        self._send_nowait([0, _KIND_PUSH, method, payload])

    async def push(self, method: str, payload: Any = None) -> None:
        self._send_nowait([0, _KIND_PUSH, method, payload])

    # -- read path -----------------------------------------------------------

    def reply_nowait(self, msgid: int, method: str, payload: Any) -> None:
        """Send a reply for a request handled by a sync handler."""
        try:
            self._send_nowait([msgid, _KIND_REP, method, payload])
        except ConnectionLost:
            pass

    def reply_error_nowait(self, msgid: int, method: str, err: str) -> None:
        try:
            self._send_nowait([msgid, _KIND_ERR, method, err])
        except ConnectionLost:
            pass

    def _on_message(self, msg) -> None:
        msgid, kind, method, payload = msg
        if kind == _KIND_REQ:
            sync_h = self._sync_handlers.get(method)
            if sync_h is not None:
                try:
                    sync_h(self, msgid, payload)
                except Exception as e:
                    self.reply_error_nowait(
                        msgid, method, f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
                    )
                return
            spawn(self._dispatch(msgid, method, payload))
        elif kind == _KIND_PUSH:
            spawn(self._dispatch(None, method, payload))
        else:
            cb = self._cb_pending.pop(msgid, None)
            if cb is not None:
                try:
                    if kind == _KIND_REP:
                        cb(payload, None)
                    else:
                        cb(None, payload)
                except Exception:
                    logger.exception("inline reply callback failed")
                return
            fut = self._pending.pop(msgid, None)
            if fut is not None and not fut.done():
                if kind == _KIND_REP:
                    fut.set_result(payload)
                else:
                    fut.set_exception(RpcError(payload))

    async def _dispatch(self, msgid, method: str, payload) -> None:
        handler = self._handlers.get(method)
        try:
            if handler is None:
                raise RpcError(f"no handler for method {method!r}")
            result = await handler(self, payload)
        except Exception as e:
            # Any handler failure — including ConnectionLost from a dial the
            # handler made to a third party — must produce an error reply, or
            # the caller waits out its full timeout.
            if msgid is not None:
                err = f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
                try:
                    self._send_nowait([msgid, _KIND_ERR, method, err])
                except ConnectionLost:
                    pass  # our own link died; caller learns via teardown
            else:
                logger.exception("push handler %s failed", method)
            return
        if msgid is not None:
            try:
                self._send_nowait([msgid, _KIND_REP, method, result])
            except ConnectionLost:
                pass

    # -- lifecycle -----------------------------------------------------------

    def _teardown(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._out.clear()
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionLost("connection closed"))
        self._pending.clear()
        if self._cb_pending:
            cbs, self._cb_pending = self._cb_pending, {}
            for cb in cbs.values():
                try:
                    cb(None, _CONNECTION_LOST)
                except Exception:
                    logger.exception("inline reply callback failed at teardown")
        try:
            if self._protocol.transport is not None:
                self._protocol.transport.close()
        except Exception:
            pass
        if self._on_close is not None:
            try:
                self._on_close(self)
            except Exception:
                logger.exception("on_close callback failed")

    async def close(self) -> None:
        self._teardown()

    @property
    def closed(self) -> bool:
        return self._closed


class Server:
    """RPC server: accepts connections, dispatches to registered handlers.

    Handlers are ``async def handler(conn, payload) -> reply``.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._port = port
        self._handlers: Dict[str, Callable] = {}
        self._sync_handlers: Dict[str, Callable] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: set = set()
        self._on_disconnect: Optional[Callable[[Connection], None]] = None

    def handler(self, name: str):
        def deco(fn):
            self._handlers[name] = fn
            return fn

        return deco

    def register(self, name: str, fn: Callable) -> None:
        self._handlers[name] = fn

    def register_sync(self, name: str, fn: Callable) -> None:
        """Register a sync fast-path handler ``fn(conn, msgid, payload)``."""
        self._sync_handlers[name] = fn

    def on_disconnect(self, fn: Callable[[Connection], None]) -> None:
        self._on_disconnect = fn

    def _make_protocol(self) -> _RpcProtocol:
        conn = Connection(
            self._handlers,
            on_close=self._conn_closed,
            sync_handlers=self._sync_handlers,
        )
        self.connections.add(conn)
        return conn._protocol

    async def start(self) -> Tuple[str, int]:
        loop = asyncio.get_running_loop()
        self._server = await loop.create_server(
            self._make_protocol, self._host, self._port
        )
        sock = self._server.sockets[0]
        self._host, self._port = sock.getsockname()[:2]
        # Same-host peers dial the Unix socket instead of TCP loopback
        # (~40% less kernel CPU per frame on the chatty control plane); the
        # path is derived from the TCP port, so the advertised (host, port)
        # address stays the only address anyone needs to know.
        try:
            path = _uds_path(self._port)
            if os.path.exists(path):
                os.unlink(path)
            self._uds_server = await loop.create_unix_server(self._make_protocol, path)
            self._uds_path = path
        except Exception:  # pragma: no cover - platform without UDS
            self._uds_server = None
            self._uds_path = None
        return self._host, self._port

    @property
    def address(self) -> Tuple[str, int]:
        return self._host, self._port

    def _conn_closed(self, conn: Connection) -> None:
        self.connections.discard(conn)
        if self._on_disconnect is not None:
            self._on_disconnect(conn)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
        if getattr(self, "_uds_server", None) is not None:
            self._uds_server.close()
            try:
                os.unlink(self._uds_path)
            except OSError:
                pass
        # Close live connections before wait_closed(): since py3.12.1
        # wait_closed blocks until every client transport is gone.
        for conn in list(self.connections):
            await conn.close()
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=5)
            except asyncio.TimeoutError:
                pass


async def connect(
    host: str,
    port: int,
    handlers: Optional[Dict[str, Callable]] = None,
    retry: int = 30,
    retry_interval: float = 0.1,
    sync_handlers: Optional[Dict[str, Callable]] = None,
) -> Connection:
    """Dial a server, retrying while it boots. Returns a duplex Connection."""
    loop = asyncio.get_running_loop()
    last_err: Optional[Exception] = None
    uds = _uds_path(port) if host in _LOOPBACK else None
    for _ in range(max(1, retry)):
        try:
            # NB: keep the caller's dict object (even if currently empty) so
            # handlers registered later are visible on this connection.
            conn = Connection(
                handlers if handlers is not None else {}, sync_handlers=sync_handlers
            )
            conn.remote_addr = (host, port)
            if uds is not None and os.path.exists(uds):
                try:
                    await loop.create_unix_connection(lambda: conn._protocol, uds)
                    return conn
                except (ConnectionRefusedError, OSError):
                    pass  # stale socket file; fall through to TCP
            await loop.create_connection(lambda: conn._protocol, host, port)
            return conn
        except (ConnectionRefusedError, OSError) as e:
            last_err = e
            await asyncio.sleep(retry_interval)
    raise ConnectionLost(f"could not connect to {host}:{port}: {last_err}")
