"""Asyncio RPC layer: length-prefixed msgpack frames over TCP.

TPU-native analog of the reference's rpc scaffolding (src/ray/rpc/): persistent
client connections with call multiplexing, a handler-registry server, and
server->client push for pubsub channels. The reference wraps gRPC; we use a
lean custom framing because every daemon here is an asyncio program and the
control-plane messages are small dicts — msgpack round-trips them with no
codegen step. Payloads that carry Python objects (task args, actor state)
are cloudpickled into opaque ``bytes`` fields by the caller.

Frame: 4-byte little-endian length + msgpack([msgid, kind, method, payload]).
Kinds: 0=request, 1=reply, 2=error-reply, 3=push (one-way).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import struct
import traceback
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

import msgpack

logger = logging.getLogger(__name__)

# The event loop holds only weak references to tasks: a fire-and-forget
# asyncio.create_task() whose result is dropped can be garbage-collected
# mid-flight (observed as lease requests silently vanishing under GC
# pressure). Every background task in the runtime goes through spawn(),
# which parks a strong reference until the task completes.
_BG_TASKS: set = set()


def spawn(coro) -> asyncio.Task:
    task = asyncio.get_running_loop().create_task(coro)
    _BG_TASKS.add(task)
    task.add_done_callback(_BG_TASKS.discard)
    return task


_KIND_REQ = 0
_KIND_REP = 1
_KIND_ERR = 2
_KIND_PUSH = 3

_LEN = struct.Struct("<I")
_MAX_FRAME = 1 << 31


class RpcError(Exception):
    """Raised on the caller when the remote handler raised or the link died."""


class ConnectionLost(RpcError):
    pass


def _pack(msg) -> bytes:
    body = msgpack.packb(msg, use_bin_type=True)
    return _LEN.pack(len(body)) + body


async def _read_frame(reader: asyncio.StreamReader):
    header = await reader.readexactly(4)
    (length,) = _LEN.unpack(header)
    if length > _MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    body = await reader.readexactly(length)
    return msgpack.unpackb(body, raw=False, strict_map_key=False)


class Connection:
    """One end of a duplex RPC link. Both sides can issue requests and pushes."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        handlers: Dict[str, Callable[..., Awaitable[Any]]],
        on_close: Optional[Callable[["Connection"], None]] = None,
    ):
        self._reader = reader
        self._writer = writer
        self._handlers = handlers
        self._on_close = on_close
        self._msgid = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._closed = False
        self._write_lock = asyncio.Lock()
        self._reader_task = asyncio.create_task(self._read_loop())
        # Arbitrary per-connection state daemons can attach (e.g. worker id).
        self.context: Dict[str, Any] = {}

    @property
    def peername(self) -> Optional[Tuple[str, int]]:
        try:
            return self._writer.get_extra_info("peername")
        except Exception:
            return None

    async def _send(self, msg) -> None:
        if self._closed:
            raise ConnectionLost("connection closed")
        data = _pack(msg)
        async with self._write_lock:
            self._writer.write(data)
            await self._writer.drain()

    async def call(self, method: str, payload: Any = None, timeout: Optional[float] = None):
        """Issue a request and await the reply."""
        msgid = next(self._msgid)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[msgid] = fut
        try:
            await self._send([msgid, _KIND_REQ, method, payload])
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(msgid, None)

    async def push(self, method: str, payload: Any = None) -> None:
        """One-way message; no reply expected."""
        await self._send([0, _KIND_PUSH, method, payload])

    async def _read_loop(self) -> None:
        try:
            while True:
                msg = await _read_frame(self._reader)
                msgid, kind, method, payload = msg
                if kind == _KIND_REQ:
                    spawn(self._dispatch(msgid, method, payload))
                elif kind == _KIND_PUSH:
                    spawn(self._dispatch(None, method, payload))
                elif kind in (_KIND_REP, _KIND_ERR):
                    fut = self._pending.get(msgid)
                    if fut is not None and not fut.done():
                        if kind == _KIND_REP:
                            fut.set_result(payload)
                        else:
                            fut.set_exception(RpcError(payload))
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        except Exception:
            logger.exception("rpc read loop failed")
        finally:
            self._teardown()

    async def _dispatch(self, msgid, method: str, payload) -> None:
        handler = self._handlers.get(method)
        try:
            if handler is None:
                raise RpcError(f"no handler for method {method!r}")
            result = await handler(self, payload)
        except Exception as e:
            # Any handler failure — including ConnectionLost from a dial the
            # handler made to a third party — must produce an error reply, or
            # the caller waits out its full timeout.
            if msgid is not None:
                err = f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
                try:
                    await self._send([msgid, _KIND_ERR, method, err])
                except ConnectionLost:
                    pass  # our own link died; caller learns via teardown
            else:
                logger.exception("push handler %s failed", method)
            return
        if msgid is not None:
            try:
                await self._send([msgid, _KIND_REP, method, result])
            except ConnectionLost:
                pass

    def _teardown(self) -> None:
        if self._closed:
            return
        self._closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionLost("connection closed"))
        self._pending.clear()
        try:
            self._writer.close()
        except Exception:
            pass
        if self._on_close is not None:
            try:
                self._on_close(self)
            except Exception:
                logger.exception("on_close callback failed")

    async def close(self) -> None:
        self._reader_task.cancel()
        self._teardown()

    @property
    def closed(self) -> bool:
        return self._closed


class Server:
    """RPC server: accepts connections, dispatches to registered handlers.

    Handlers are ``async def handler(conn, payload) -> reply``.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._port = port
        self._handlers: Dict[str, Callable] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: set = set()
        self._on_disconnect: Optional[Callable[[Connection], None]] = None

    def handler(self, name: str):
        def deco(fn):
            self._handlers[name] = fn
            return fn

        return deco

    def register(self, name: str, fn: Callable) -> None:
        self._handlers[name] = fn

    def on_disconnect(self, fn: Callable[[Connection], None]) -> None:
        self._on_disconnect = fn

    async def start(self) -> Tuple[str, int]:
        self._server = await asyncio.start_server(self._accept, self._host, self._port)
        sock = self._server.sockets[0]
        self._host, self._port = sock.getsockname()[:2]
        return self._host, self._port

    @property
    def address(self) -> Tuple[str, int]:
        return self._host, self._port

    async def _accept(self, reader, writer) -> None:
        conn = Connection(reader, writer, self._handlers, on_close=self._conn_closed)
        self.connections.add(conn)

    def _conn_closed(self, conn: Connection) -> None:
        self.connections.discard(conn)
        if self._on_disconnect is not None:
            self._on_disconnect(conn)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
        # Close live connections before wait_closed(): since py3.12.1
        # wait_closed blocks until every client transport is gone.
        for conn in list(self.connections):
            await conn.close()
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=5)
            except asyncio.TimeoutError:
                pass


async def connect(
    host: str,
    port: int,
    handlers: Optional[Dict[str, Callable]] = None,
    retry: int = 30,
    retry_interval: float = 0.1,
) -> Connection:
    """Dial a server, retrying while it boots. Returns a duplex Connection."""
    last_err: Optional[Exception] = None
    for _ in range(max(1, retry)):
        try:
            reader, writer = await asyncio.open_connection(host, port)
            # NB: keep the caller's dict object (even if currently empty) so
            # handlers registered later are visible on this connection.
            return Connection(reader, writer, handlers if handlers is not None else {})
        except (ConnectionRefusedError, OSError) as e:
            last_err = e
            await asyncio.sleep(retry_interval)
    raise ConnectionLost(f"could not connect to {host}:{port}: {last_err}")
