"""Pluggable accelerator managers (reference: python/ray/_private/accelerators/).

The registry mirrors the reference's ``get_all_accelerator_managers`` /
``get_accelerator_manager_for_resource``: each manager knows how to detect
its hardware on the current host and what extra gang resources to advertise.
The TPU manager reproduces TPUAcceleratorManager's probe order
(tpu.py:104-120): explicit env overrides, device files, then GCE/GKE
instance metadata — so a raylet on a Cloud TPU VM discovers its pod slice
without any configuration.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional, Type

logger = logging.getLogger(__name__)


class AcceleratorManager:
    """One accelerator family's detection + resource surface."""

    @staticmethod
    def get_resource_name() -> str:
        raise NotImplementedError

    @staticmethod
    def detect_count() -> int:
        raise NotImplementedError

    @staticmethod
    def get_additional_resources() -> Dict[str, float]:
        """Extra resources to advertise alongside the chip count (e.g. the
        TPU pod-slice gang resource)."""
        return {}


def _gce_metadata(path: str, timeout: float = 0.5) -> Optional[str]:
    """Read one GCE/GKE instance-metadata value (reference: tpu.py queries
    the metadata server for accelerator-type / agent-worker-number). Returns
    None off-GCE (fast: connection refused / DNS failure within timeout)."""
    host = os.environ.get("GCE_METADATA_HOST", "metadata.google.internal")
    url = f"http://{host}/computeMetadata/v1/instance/{path}"
    try:
        import urllib.request

        req = urllib.request.Request(url, headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read().decode().strip()
    except Exception:
        return None


class TPUAcceleratorManager(AcceleratorManager):
    """Reference: TPUAcceleratorManager (accelerators/tpu.py:75,104-120,199).

    Chip count: TPU_VISIBLE_CHIPS / RAY_TPU_CHIPS env, else /dev/accel*,
    else /dev/vfio entries. Pod slice: TPU_POD_TYPE / TPU_ACCELERATOR_TYPE
    env, else GCE metadata ``attributes/accelerator-type``; worker index:
    TPU_WORKER_ID env, else metadata ``attributes/agent-worker-number``.
    Worker 0 of a slice additionally advertises ``TPU-{type}-head: 1`` — the
    gang resource a pod-slice placement targets (tpu.py:382)."""

    @staticmethod
    def get_resource_name() -> str:
        return "TPU"

    @staticmethod
    def detect_count() -> int:
        env_chips = os.environ.get("TPU_VISIBLE_CHIPS") or os.environ.get(
            "RAY_TPU_CHIPS"
        )
        if env_chips:
            return len([c for c in env_chips.split(",") if c.strip()])
        count = 0
        for i in range(16):
            if os.path.exists(f"/dev/accel{i}") or os.path.exists(f"/dev/accel_{i}"):
                count += 1
        if count == 0 and os.path.isdir("/dev/vfio"):
            count = len([e for e in os.listdir("/dev/vfio") if e.isdigit()])
        return count

    @staticmethod
    def get_current_pod_type() -> Optional[str]:
        pod_type = os.environ.get("TPU_POD_TYPE") or os.environ.get(
            "TPU_ACCELERATOR_TYPE"
        )
        if pod_type:
            return pod_type
        return _gce_metadata("attributes/accelerator-type")

    @staticmethod
    def get_current_worker_id() -> Optional[int]:
        wid = os.environ.get("TPU_WORKER_ID")
        if wid is None:
            wid = _gce_metadata("attributes/agent-worker-number")
        try:
            return int(wid) if wid is not None else None
        except ValueError:
            return None

    @classmethod
    def get_additional_resources(cls) -> Dict[str, float]:
        out: Dict[str, float] = {}
        pod_type = cls.get_current_pod_type()
        if pod_type:
            worker_id = cls.get_current_worker_id()
            if worker_id in (0, None):
                out[f"TPU-{pod_type}-head"] = 1.0
            # Version label resource (reference: accelerator_type:TPU-V4) —
            # lets tasks target a TPU generation without naming the slice.
            version = pod_type.split("-")[0]
            out[f"accelerator_type:TPU-{version.upper()}"] = 1.0
        return out

    @staticmethod
    def get_num_workers_in_pod(pod_type: str, chips_per_host: int = 4) -> int:
        """Hosts in a slice of ``pod_type`` (e.g. v4-16 -> 16 chips / 4 per
        host -> 4... actually v4 counts cores: 16 cores = 8 chips = 2 hosts).
        Mirrors tpu.py:199 get_num_tpu_visible_chips_per_host heuristics."""
        try:
            version, size = pod_type.split("-", 1)
            n = int(size)
        except (ValueError, AttributeError):
            return 1
        if version in ("v2", "v3", "v4"):
            chips = n // 2  # these report cores; 2 cores per chip
        else:
            chips = n  # v5e/v5p/v6e report chips
        return max(1, chips // max(1, chips_per_host))


_MANAGERS: List[Type[AcceleratorManager]] = [TPUAcceleratorManager]


def register_accelerator_manager(manager: Type[AcceleratorManager]) -> None:
    if manager not in _MANAGERS:
        _MANAGERS.append(manager)


def get_all_accelerator_managers() -> List[Type[AcceleratorManager]]:
    return list(_MANAGERS)


def get_accelerator_manager_for_resource(
    resource_name: str,
) -> Optional[Type[AcceleratorManager]]:
    for m in _MANAGERS:
        if m.get_resource_name() == resource_name:
            return m
    return None


def detect_accelerator_resources() -> Dict[str, float]:
    """Aggregate every registered manager's view of this host."""
    resources: Dict[str, float] = {}
    for m in _MANAGERS:
        try:
            count = m.detect_count()
        except Exception:
            logger.exception("accelerator detection failed for %s", m.__name__)
            continue
        if count:
            resources[m.get_resource_name()] = float(count)
            resources.update(m.get_additional_resources())
    return resources
