"""Node bring-up: owns the GCS + raylet for a head node.

Analog of the reference's python/ray/_private/node.py (start_ray_processes). Two
modes:
- in-loop (default): GCS and raylet run as asyncio servers on the driver's
  background event loop — same wire protocol as separate processes (workers
  still connect over TCP), minus process-spawn latency. This is also how
  cluster_utils boots extra "nodes" for multi-node tests.
- subprocess: daemons run as their own processes (``python -m
  ray_tpu._private.gcs`` / ``raylet``) for deployment-shaped setups.
"""

from __future__ import annotations

import asyncio
import os
import secrets
import time
from typing import Dict, Optional, Tuple

from ray_tpu._private.common import config
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.raylet import Raylet


class Node:
    def __init__(
        self,
        *,
        head: bool = True,
        gcs_addr: Optional[Tuple[str, int]] = None,
        num_cpus: Optional[float] = None,
        num_tpus: Optional[float] = None,
        resources: Optional[Dict[str, float]] = None,
        object_store_memory: Optional[int] = None,
        session_name: Optional[str] = None,
        labels: Optional[Dict[str, str]] = None,
        worker_env: Optional[Dict[str, str]] = None,
    ):
        self.head = head
        self.session_name = session_name or f"s{int(time.time())}_{secrets.token_hex(4)}"
        self.gcs_server: Optional[GcsServer] = None
        self.gcs_addr = gcs_addr
        self.raylet: Optional[Raylet] = None
        self.raylet_addr: Optional[Tuple[str, int]] = None
        self._resources = dict(resources or {})
        if num_cpus is not None:
            self._resources["CPU"] = float(num_cpus)
        if num_tpus is not None:
            self._resources["TPU"] = float(num_tpus)
        if "CPU" not in self._resources:
            self._resources["CPU"] = float(os.cpu_count() or 1)
        if "TPU" not in self._resources:
            from ray_tpu._private.raylet import detect_tpu_resources

            self._resources.update(detect_tpu_resources())
        self.object_store_memory = object_store_memory
        self.labels = labels
        self.worker_env = worker_env
        self.gcs_standby = None  # GcsStandby when HA mode is on (head only)

    def gcs_persist_path(self) -> str:
        """Session-scoped store file backing GCS fault tolerance (WAL or
        sqlite per the ``gcs_persist_backend`` knob; gcs_store.make_store)."""
        import tempfile

        return os.path.join(
            tempfile.gettempdir(), f"ray_tpu_{self.session_name}", "gcs.db"
        )

    def ha_enabled(self) -> bool:
        """HA control plane: replicated store + warm standby + leader file
        (docs/fault_tolerance.md "HA deployment")."""
        return bool(
            config.gcs_persistence and config.gcs_persist_backend == "replicated"
        )

    def gcs_leader_file(self) -> Optional[str]:
        if not self.ha_enabled():
            return None
        from ray_tpu._private import gcs_ha

        return gcs_ha.leader_file_path(self.gcs_persist_path())

    async def _arm_standby(self) -> None:
        from ray_tpu._private.gcs_ha import GcsStandby

        self.gcs_standby = GcsStandby(
            session_name=self.session_name,
            persist_path=self.gcs_persist_path(),
        )
        await self.gcs_standby.start()

    async def start(self) -> None:
        if self.head:
            self.gcs_server = GcsServer(
                session_name=self.session_name,
                persist_path=(
                    self.gcs_persist_path() if config.gcs_persistence else None
                ),
            )
            self.gcs_addr = await self.gcs_server.start()
            if self.ha_enabled():
                await self._arm_standby()
        assert self.gcs_addr is not None
        self.raylet = Raylet(
            self.gcs_addr,
            self.session_name,
            resources=self._resources,
            object_store_memory=self.object_store_memory,
            labels=self.labels,
            worker_env=self.worker_env,
            gcs_leader_file=self.gcs_leader_file(),
        )
        self.raylet_addr = await self.raylet.start()

    async def stop(self) -> None:
        if self.raylet is not None:
            await self.raylet.stop()
        if self.gcs_standby is not None:
            # The promoted standby's server may be the very server we adopted
            # as gcs_server; detach it so it is stopped exactly once below.
            if self.gcs_standby.server is self.gcs_server:
                self.gcs_standby.server = None
            await self.gcs_standby.stop()
        if self.gcs_server is not None:
            await self.gcs_server.stop()
            if self.head and config.gcs_persistence:
                # Final shutdown: the session is over, drop its durable state
                # (restarts go through kill_gcs/restart_gcs, not stop()).
                # The loop is about to exit; there is nothing left to stall.
                import shutil

                shutil.rmtree(  # aio-lint: disable=blocking-call
                    os.path.dirname(self.gcs_persist_path()), ignore_errors=True
                )

    async def kill_gcs(self) -> None:
        """Fault-injection: stop the GCS process, keeping raylets/workers up."""
        assert self.gcs_server is not None
        await self.gcs_server.stop()

    async def crash_gcs(self, torn_tail: bool = False) -> None:
        """Fault-injection: hard-crash the GCS (kill -9 shaped) — no store
        checkpoint, no final fsync, no graceful teardown of persistence.
        ``torn_tail=True`` additionally appends a half-written record to the
        WAL, simulating power loss mid-write; recovery must truncate it."""
        assert self.gcs_server is not None
        await self.gcs_server.crash()
        if torn_tail and config.gcs_persistence:
            from ray_tpu._private.gcs_store import inject_torn_tail

            inject_torn_tail(self.gcs_persist_path())

    async def kill_gcs_host(self, timeout: float = 30.0) -> Tuple[str, int]:
        """Fault-injection: lose the whole GCS *machine* — the process dies
        hard AND its local log member is gone (disk went with the host).
        The warm standby notices the unrenewed lease, promotes over the
        surviving follower log at term+1, and the leader pointer file
        re-targets every client. Returns the new leader's address."""
        assert self.gcs_server is not None and self.gcs_standby is not None
        from ray_tpu._private.gcs_store import drop_host

        await self.gcs_server.crash()
        drop_host(self.gcs_persist_path())
        return await self.adopt_promoted_gcs(timeout)

    async def adopt_promoted_gcs(self, timeout: float = 30.0) -> Tuple[str, int]:
        """Wait for the armed standby to promote, adopt its server as this
        node's GCS, and re-arm a fresh standby. Used after any leader loss
        the standby must absorb — a killed host, or a leader that demoted
        itself on losing its replication majority."""
        assert self.gcs_standby is not None
        await asyncio.wait_for(self.gcs_standby.promoted.wait(), timeout)
        self.gcs_server = self.gcs_standby.server
        self.gcs_addr = self.gcs_server.server.address
        # Re-arm: a fresh standby guards the new leader so a second failover
        # works the same way.
        await self._arm_standby()
        return self.gcs_addr

    async def restart_gcs(self) -> None:
        """Restart the GCS on the same address from its persisted state.
        Raylets re-register over their reconnecting GCS clients; detached
        actors and KV survive (reference: GCS FT with Redis persistence +
        NotifyGCSRestart, node_manager.proto:373)."""
        assert self.gcs_addr is not None
        self.gcs_server = GcsServer(
            host=self.gcs_addr[0],
            port=self.gcs_addr[1],
            session_name=self.session_name,
            persist_path=(
                self.gcs_persist_path() if config.gcs_persistence else None
            ),
        )
        await self.gcs_server.start()

    @property
    def node_id(self) -> str:
        return self.raylet.node_id if self.raylet else ""
