"""Shared-memory segment abstraction: native C++ extension with pure-Python fallback.

The native path (ray_tpu._native._shm, src/shm_buffer.cc) maps POSIX shm
segments directly; the fallback uses multiprocessing.shared_memory with its
resource tracker disabled for attachments (the raylet owns segment lifetime,
not whichever process happened to touch it last).
"""

from __future__ import annotations

from typing import Optional

try:
    from ray_tpu._native import _shm as _native_shm

    NATIVE = True
except ImportError:  # pragma: no cover - exercised only in pure-python installs
    _native_shm = None
    NATIVE = False


class Segment:
    """A named shared-memory segment with a memoryview interface."""

    __slots__ = ("name", "_buf", "_view", "writable")

    def __init__(self, name: str, buf, writable: bool):
        self.name = name
        self._buf = buf
        self.writable = writable
        self._view: Optional[memoryview] = None

    @property
    def view(self) -> memoryview:
        if self._view is None:
            self._view = memoryview(self._buf)
        return self._view

    @property
    def size(self) -> int:
        return self.view.nbytes

    def close(self) -> None:
        if self._view is not None:
            self._view.release()
            self._view = None
        if _native_shm is not None and isinstance(self._buf, _native_shm.ShmBuffer):
            if not self._buf.closed:
                self._buf.close()
        else:  # multiprocessing fallback
            self._buf.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


if NATIVE and hasattr(_native_shm, "copy_nt"):
    # Non-temporal (cache-bypassing) copy for large writes into shm. Fresh
    # arena regions are never cache-resident, so regular stores pay a
    # read-for-ownership on every line; streaming stores skip it (measured
    # ~4.8x over a memoryview slice assign for 16 MiB on cold pages).
    copy_into = _native_shm.copy_nt
else:  # pragma: no cover - pure-python installs

    def copy_into(dst, src) -> None:
        src = memoryview(src).cast("B")
        dst[: src.nbytes] = src


if NATIVE:

    def create(name: str, size: int) -> Segment:
        return Segment(name, _native_shm.create("/" + name, size), writable=True)

    def open_ro(name: str) -> Segment:
        return Segment(name, _native_shm.open_ro("/" + name), writable=False)

    def open_rw(name: str) -> Segment:
        return Segment(name, _native_shm.open_rw("/" + name), writable=True)

    def unlink(name: str) -> None:
        _native_shm.unlink("/" + name)

else:  # pragma: no cover
    from multiprocessing import resource_tracker, shared_memory

    class _Shm(shared_memory.SharedMemory):
        # Detach from the resource tracker: lifetime is managed by the raylet.
        def __init__(self, name, create=False, size=0):
            super().__init__(name=name, create=create, size=size)
            if not create:
                try:
                    resource_tracker.unregister(self._name, "shared_memory")
                except Exception:
                    pass

    class _FallbackBuf:
        def __init__(self, shm):
            self.shm = shm

        def __buffer__(self, flags):
            return self.shm.buf.__buffer__(flags)

        def close(self):
            self.shm.close()

    def create(name: str, size: int) -> Segment:
        shm = _Shm(name, create=True, size=size)
        return Segment(name, _FallbackBuf(shm), True)

    def open_ro(name: str) -> Segment:
        shm = _Shm(name)
        return Segment(name, _FallbackBuf(shm), False)

    def open_rw(name: str) -> Segment:
        shm = _Shm(name)
        return Segment(name, _FallbackBuf(shm), True)

    def unlink(name: str) -> None:
        try:
            shm = shared_memory.SharedMemory(name=name)
            shm.close()
            shm.unlink()
        except FileNotFoundError:
            pass
