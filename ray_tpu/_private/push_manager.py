"""Source-side object push fan-out with bounded in-flight chunks.

Analog of the reference's PushManager (src/ray/object_manager/push_manager.h):
when many nodes need one object (a broadcast argument, a shared dataset
block), each destination's pull triggers a *push* from the source raylet.
The source streams chunks as one-way messages (no per-chunk round trip) and
caps chunks in flight **across all destinations**, so a 1 GiB broadcast to 50
nodes neither oversubscribes the NIC nor serializes on request/reply latency.
Duplicate (object, destination) pushes coalesce onto one in-flight transfer
(reference dedup: push_manager.h push_info_ bookkeeping).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, Tuple

from ray_tpu._private import rpc, telemetry
from ray_tpu._private.common import adaptive_chunk_size, config

logger = logging.getLogger(__name__)

_TEL_PUSHES = telemetry.counter(
    "object", "pushes_completed", "source-side object pushes completed"
)
_TEL_PUSH_CHUNKS = telemetry.counter(
    "object", "push_chunks_sent", "one-way data chunks streamed to peers"
)
_TEL_PUSH_BYTES = telemetry.counter(
    "object", "transfer_bytes_out", "object bytes pushed to remote nodes"
)


class PushManager:
    def __init__(self, raylet) -> None:
        self.raylet = raylet
        # (oid, dest) -> future resolving when the push lands (dedup).
        self.active: Dict[Tuple[str, Tuple[str, int]], asyncio.Future] = {}
        # Cached outbound data-plane connections, one per destination;
        # `_conn_futs` coalesces concurrent dials to a fresh destination.
        self._conns: Dict[Tuple[str, int], rpc.Connection] = {}
        self._conn_futs: Dict[Tuple[str, int], asyncio.Future] = {}
        # Global chunk budget across all destinations.
        self._sem = asyncio.Semaphore(max(1, config.push_manager_max_chunks))
        self.stats = {  # telemetry: allow-adhoc-stats (pre-telemetry node_stats surface)
            "pushes_started": 0,
            "pushes_completed": 0,
            "pushes_deduped": 0,
            "chunks_sent": 0,
            "inflight_chunks": 0,
            "peak_inflight_chunks": 0,
        }

    async def push(self, oid: str, dest: Tuple[str, int]) -> None:
        """Push one object to one destination; coalesces with an identical
        in-flight push. Raises on failure (caller falls back to chunk pull)."""
        key = (oid, dest)
        fut = self.active.get(key)
        if fut is not None:
            self.stats["pushes_deduped"] += 1
            await asyncio.shield(fut)
            return
        fut = asyncio.get_running_loop().create_future()
        self.active[key] = fut
        self.stats["pushes_started"] += 1
        t0 = time.monotonic()
        ws = time.time()
        try:
            await self._do_push(oid, dest)
            self.stats["pushes_completed"] += 1
            _TEL_PUSHES.inc()
            if rpc._trace_ctx.get() is not None:
                from ray_tpu.util import tracing

                tracing.record_span(
                    "object.push",
                    "object",
                    ws,
                    time.monotonic() - t0,
                    oid=oid,
                    dest=f"{dest[0]}:{dest[1]}",
                )
            fut.set_result(True)
        except BaseException as e:
            if not fut.done():
                fut.set_exception(e)
            # The shielded waiters consume the exception; ours re-raises.
            fut.exception()
            raise
        finally:
            self.active.pop(key, None)

    async def _do_push(self, oid: str, dest: Tuple[str, int]) -> None:
        r = self.raylet
        await r._restore_with_backpressure(oid)
        info = r.store.lookup(oid)
        if info is None or not info[2]:
            raise rpc.RpcError(f"push source missing object {oid[:12]}")
        off, size, _, _ = info
        # Pin against eviction/spill while chunk reads are in flight.
        token = f"push:{oid}:{dest}"
        holds = r.obj_holds.setdefault(oid, {})
        holds[token] = holds.get(token, 0) + 1
        try:
            conn = await self._get_conn(dest)
            start = await conn.call(
                "PushStart",
                {"oid": oid, "size": size},
                timeout=config.rpc_chunk_timeout_s,
            )
            if not start.get("needed"):
                return  # destination already has (or is assembling) it
            chunk = adaptive_chunk_size(size)
            sent = 0
            while sent < size:
                n = min(chunk, size - sent)
                await self._sem.acquire()
                self.stats["inflight_chunks"] += 1
                self.stats["peak_inflight_chunks"] = max(
                    self.stats["peak_inflight_chunks"],
                    self.stats["inflight_chunks"],
                )
                try:
                    # Zero-copy send: the arena view goes to the transport as
                    # a blob sidecar inside this call (the obj_holds pin
                    # covers the synchronous write window; an unwritable
                    # socket copies into asyncio's own buffer).
                    conn.blob_push_nowait(
                        "PushChunk",
                        {"oid": oid, "offset": sent},
                        r.arena.view[off + sent : off + sent + n],
                    )
                    # TCP backpressure: wait for the socket buffer to fall
                    # below the high-water mark before the next chunk — but
                    # bounded: a wedged destination (zero-window, stuck loop)
                    # must not pin a global chunk-budget slot forever.
                    try:
                        await asyncio.wait_for(
                            conn.drain(), timeout=config.rpc_drain_timeout_s
                        )
                    except asyncio.TimeoutError:
                        await conn.close()  # dest aborts assembly on the drop
                        self._conns.pop(dest, None)
                        raise rpc.RpcError(
                            f"push to {dest} stalled (drain timeout)"
                        )
                    self.stats["chunks_sent"] += 1
                    _TEL_PUSH_CHUNKS.inc()
                    _TEL_PUSH_BYTES.inc(n)
                finally:
                    self.stats["inflight_chunks"] -= 1
                    self._sem.release()
                sent += n
        finally:
            holds = r.obj_holds.get(oid)
            if holds is not None:
                if holds.get(token, 0) <= 1:
                    holds.pop(token, None)
                else:
                    holds[token] -= 1
                if not holds:
                    del r.obj_holds[oid]

    async def _get_conn(self, dest: Tuple[str, int]) -> rpc.Connection:
        while True:
            conn = self._conns.get(dest)
            if conn is not None and not conn.closed:
                return conn
            fut = self._conn_futs.get(dest)
            if fut is not None:
                # Another push is already dialing this destination.
                conn = await asyncio.shield(fut)
                if not conn.closed:
                    return conn
                continue
            fut = asyncio.get_running_loop().create_future()
            self._conn_futs[dest] = fut
            try:
                conn = await rpc.connect(*dest, retry=3)
                self._conns[dest] = conn
                fut.set_result(conn)
                return conn
            except BaseException as e:
                fut.set_exception(e)
                fut.exception()  # consumed here; waiters get their own copy
                raise
            finally:
                self._conn_futs.pop(dest, None)

    async def close(self) -> None:
        for conn in self._conns.values():
            try:
                await conn.close()
            except Exception:
                pass
        self._conns.clear()
