"""Runtime telemetry plane: hot-path metrics core + flight recorder.

Analog of the reference's internal stats pipeline (src/ray/stats/metric.h
feeding the MetricsAgent, python/ray/_private/metrics_agent.py), but for
*this* runtime's own counters — the numbers that previously lived in
ad-hoc dicts (``rpc.deadline_stats``, ``RetryableConnection.stats``, the
raylet grant ledger, plasma push/pull counters, ``router.stats()``) and
died with the process. Application metrics keep their own pipeline
(``ray_tpu/util/metrics.py``); the dashboard merges both exports on
``/metrics``.

Design constraints, in order:

1. **Amortized-zero-cost record.** Instrumentation sites bind a *cell*
   once (module import / object construction) and the hot path is a bound
   method doing one float add — no dict lookup, no lock, no branch on a
   config flag. Everything here runs on the owning process's event loop
   (or is tolerant of a lost increment under the GIL), so cells are
   lock-free; locks guard only registration, which is cold.
2. **Snapshot-and-reset flush.** ``flush_delta()`` drains counters and
   histograms as additive deltas with no awaits between read and reset
   (same contract as worker_main's ``_deadline_stats_delta``), so flushes
   from multiple drainers in one process — e.g. an in-process raylet's
   flush loop racing the GCS's local drain — each carry a disjoint slice
   and the aggregate stays exactly-once. Gauges report last value and are
   never reset.
3. **One wire shape.** The same payload rides ``ReportTelemetry`` (worker
   subprocess -> GCS), the GCS's local drain, and ``loadgen --json``; the
   GCS folds it into one aggregate keyed by (component, node, name) and
   the dashboard renders that as Prometheus text.

The **flight recorder** is a fixed-size ring of structured lifecycle
events (lease granted/released, object sealed/freed, actor state edges,
retry/redial, shed/enforce, replica evict). ``record_event`` appends a
tuple — cheap enough for hot paths. The flusher drains local events to
the GCS's merged ring; the chaos runner dumps ring + aggregate into a
time-ordered JSONL timeline next to the failing seed on any invariant
violation.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private.common import config

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

# Default latency buckets (seconds): microseconds to tens of seconds.
LATENCY_BUCKETS_S = (
    0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0
)
# Default size buckets (bytes): 256 B to 256 MiB.
SIZE_BUCKETS = (
    256, 4096, 65536, 1 << 20, 16 << 20, 256 << 20
)


def _labels_key(labels: Dict[str, str]) -> str:
    return json.dumps(sorted(labels.items()))


class _Cell:
    """One (family, labelset) scalar. ``inc``/``set`` are the hot path."""

    __slots__ = ("v",)

    def __init__(self):
        self.v = 0.0

    def inc(self, n: float = 1.0) -> None:
        self.v += n

    def set(self, v: float) -> None:
        self.v = v


class _HistCell:
    """One (family, labelset) fixed-bucket histogram."""

    __slots__ = ("bounds", "counts", "sum", "total")

    def __init__(self, bounds: Sequence[float]):
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)
        self.sum = 0.0
        self.total = 0

    def observe(self, v: float) -> None:
        # Linear scan beats bisect for <=12 buckets and avoids an import;
        # typical observations land in the first few buckets anyway.
        for i, b in enumerate(self.bounds):
            if v <= b:
                self.counts[i] += 1
                break
        else:
            self.counts[-1] += 1
        self.sum += v
        self.total += 1


class Family:
    """A named metric and its per-labelset cells.

    Hot paths call ``family.cell(**labels)`` once at bind time and then
    ``cell.inc(...)`` forever after; ``family.inc()`` etc. operate on the
    unlabeled default cell for sites without label dimensions.
    """

    __slots__ = (
        "component", "name", "kind", "help", "buckets", "_cells", "_default"
    )

    def __init__(self, component, name, kind, help="", buckets=None):
        self.component = component
        self.name = name
        self.kind = kind
        self.help = help
        self.buckets = tuple(buckets) if buckets else None
        self._cells: Dict[str, Any] = {}
        self._default = None

    def _new_cell(self):
        if self.kind == HISTOGRAM:
            return _HistCell(self.buckets or LATENCY_BUCKETS_S)
        return _Cell()

    def cell(self, **labels):
        key = _labels_key(labels)
        c = self._cells.get(key)
        if c is None:
            c = self._cells[key] = self._new_cell()
        return c

    @property
    def default(self):
        c = self._default
        if c is None:
            c = self._default = self.cell()
        return c

    # Convenience passthroughs for unlabeled sites.
    def inc(self, n: float = 1.0) -> None:
        self.default.inc(n)

    def set(self, v: float) -> None:
        self.default.set(v)

    def observe(self, v: float) -> None:
        self.default.observe(v)


_registry_lock = threading.Lock()
_registry: Dict[Tuple[str, str], Family] = {}


def _family(component: str, name: str, kind: str, help: str, buckets=None) -> Family:
    key = (component, name)
    with _registry_lock:
        fam = _registry.get(key)
        if fam is None:
            fam = _registry[key] = Family(component, name, kind, help, buckets)
        return fam


def counter(component: str, name: str, help: str = "") -> Family:
    """Monotonic counter, flushed as additive deltas. Rendered with a
    Prometheus ``_total`` suffix."""
    return _family(component, name, COUNTER, help)


def gauge(component: str, name: str, help: str = "") -> Family:
    """Point-in-time value; last writer wins, never reset. Stale gauges
    (source stopped flushing) age out of the export."""
    return _family(component, name, GAUGE, help)


def histogram(
    component: str, name: str, help: str = "", buckets: Sequence[float] = ()
) -> Family:
    """Fixed-bucket histogram, flushed as additive bucket-count deltas."""
    return _family(component, name, HISTOGRAM, help, buckets or None)


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Fixed-size ring of structured lifecycle events.

    Each entry is ``(wall_ts, component, event, fields)``; wall-clock
    timestamps let rings from different processes merge into one ordered
    timeline (the loop-time clocks are per-process).
    """

    def __init__(self, capacity: Optional[int] = None):
        self._ring: deque = deque(
            maxlen=capacity or config.telemetry_flight_capacity
        )

    def record(self, component: str, event: str, **fields) -> None:
        self._ring.append((time.time(), component, event, fields))

    def snapshot(self) -> List[tuple]:
        return list(self._ring)

    def drain(self) -> List[tuple]:
        evs = list(self._ring)
        self._ring.clear()
        return evs

    def clear(self) -> None:
        self._ring.clear()

    def __len__(self) -> int:
        return len(self._ring)


_flight: Optional[FlightRecorder] = None


def flight() -> FlightRecorder:
    global _flight
    if _flight is None:
        _flight = FlightRecorder()
    return _flight


def record_event(component: str, event: str, **fields) -> None:
    """Append one lifecycle event to this process's ring (hot-path safe:
    a deque append)."""
    fl = _flight
    if fl is None:
        fl = flight()
    fl._ring.append((time.time(), component, event, fields))


def events_to_wire(events: List[tuple]) -> List[list]:
    return [[ts, comp, ev, fields] for ts, comp, ev, fields in events]


# ---------------------------------------------------------------------------
# Snapshot-and-reset flush (per-process side)
# ---------------------------------------------------------------------------


def _collect(reset: bool) -> List[dict]:
    """Serialize every family with non-empty state; optionally drain
    counters/histograms (gauges always report-and-keep). No awaits — the
    caller relies on read-and-reset being one atomic loop step."""
    with _registry_lock:
        fams = list(_registry.values())
    out: List[dict] = []
    for fam in fams:
        series: List[list] = []
        for key, cell in fam._cells.items():
            if fam.kind == HISTOGRAM:
                if cell.total == 0:
                    continue
                series.append(
                    [key, {"counts": list(cell.counts), "sum": cell.sum,
                           "total": cell.total}]
                )
                if reset:
                    cell.counts = [0] * len(cell.counts)
                    cell.sum = 0.0
                    cell.total = 0
            else:
                if fam.kind == COUNTER and cell.v == 0:
                    continue
                series.append([key, cell.v])
                if reset and fam.kind == COUNTER:
                    cell.v = 0.0
        if not series:
            continue
        out.append(
            {
                "c": fam.component,
                "n": fam.name,
                "k": fam.kind,
                "h": fam.help,
                "b": list(fam.buckets) if fam.buckets else None,
                "s": series,
            }
        )
    return out


def flush_delta(
    source: str, node: str, drain_events: bool = True
) -> Optional[dict]:
    """Snapshot-and-reset this process's telemetry as a ReportTelemetry
    payload; None when there is nothing to report."""
    metrics = _collect(reset=True)
    events = events_to_wire(flight().drain()) if drain_events else []
    if not metrics and not events:
        return None
    payload = {"source": source, "node": node, "metrics": metrics}
    if events:
        payload["events"] = events
    return payload


def restore_delta(payload: dict) -> None:
    """Fold an undelivered flush back into the local cells so the next
    flush carries it (same at-least-once compromise as
    worker_main._restore_deadline_delta; ReportTelemetry is RETRY_NONE)."""
    for m in payload.get("metrics", []):
        fam = _family(m["c"], m["n"], m["k"], m.get("h", ""), m.get("b"))
        for key, val in m["s"]:
            labels = dict(json.loads(key))
            cell = fam.cell(**labels)
            if fam.kind == HISTOGRAM:
                cell.counts = [a + b for a, b in zip(cell.counts, val["counts"])]
                cell.sum += val["sum"]
                cell.total += val["total"]
            elif fam.kind == COUNTER:
                cell.v += val
            # gauges were not reset; nothing to restore
    ring = flight()._ring
    for ts, comp, ev, fields in reversed(payload.get("events", [])):
        ring.appendleft((ts, comp, ev, fields))


def peek(source: str = "local", node: str = "local") -> dict:
    """Non-destructive snapshot in the same wire shape (loadgen --json)."""
    return {
        "source": source,
        "node": node,
        "metrics": _collect(reset=False),
        "events_pending": len(flight()),
    }


def reset_all() -> None:
    """Zero every cell and clear the flight ring (chaos per-seed reset,
    tests). Families stay registered — bound cells keep working."""
    with _registry_lock:
        fams = list(_registry.values())
    for fam in fams:
        for cell in fam._cells.values():
            if fam.kind == HISTOGRAM:
                cell.counts = [0] * len(cell.counts)
                cell.sum = 0.0
                cell.total = 0
            else:
                cell.v = 0.0
    flight().clear()


# ---------------------------------------------------------------------------
# Periodic flusher (one per process, whoever has a GCS channel first)
# ---------------------------------------------------------------------------

_flusher_started = False


def flusher_active() -> bool:
    return _flusher_started


async def flush_once(call: Callable, source: str, node: str) -> None:
    payload = flush_delta(source, node)
    if payload is None:
        return
    try:
        await call("ReportTelemetry", payload)
    except Exception:
        restore_delta(payload)


def start_flusher(call: Callable, source: str, node: str) -> bool:
    """Start this process's periodic telemetry flush loop. Idempotent:
    the first caller (driver CoreWorker, worker CoreWorker, or a raylet
    running in its own process) wins; extra calls are no-ops so an
    in-process cluster doesn't flush the shared registry N times.
    ``call`` is an async (method, payload) -> reply over a GCS channel.
    Returns True when this call started the loop."""
    global _flusher_started
    interval = config.telemetry_flush_interval_s
    if _flusher_started or not config.telemetry_enabled or interval <= 0:
        return False
    _flusher_started = True

    async def _loop():
        import asyncio

        while True:
            await asyncio.sleep(interval)
            await flush_once(call, source, node)

    from ray_tpu._private import rpc  # lazy: rpc imports telemetry

    rpc.spawn(_loop())
    return True


def reset_flusher_for_test() -> None:
    global _flusher_started
    _flusher_started = False


# ---------------------------------------------------------------------------
# GCS-side aggregate, keyed by (component, node, name)
# ---------------------------------------------------------------------------


def new_aggregate() -> dict:
    """The GCS's cluster-wide runtime-metric state. Wire-friendly from
    the start: GetTelemetry returns it verbatim. Series keys are
    ``"<node>|<labels_json>"``."""
    return {"meta": {}, "counters": {}, "hists": {}, "gauges": {}}


def ingest(agg: dict, payload: dict, now: Optional[float] = None) -> None:
    """Fold one ReportTelemetry payload (additive deltas) into the
    aggregate. Counter/histogram deltas accumulate; gauges overwrite with
    a receive timestamp so the renderer can age out dead sources."""
    now = time.time() if now is None else now
    node = payload.get("node", "?")
    for m in payload.get("metrics", []):
        mkey = f"{m['c']}.{m['n']}"
        meta = agg["meta"].get(mkey)
        if meta is None:
            agg["meta"][mkey] = {
                "kind": m["k"], "help": m.get("h", ""), "buckets": m.get("b")
            }
        for lkey, val in m["s"]:
            skey = f"{node}|{lkey}"
            if m["k"] == HISTOGRAM:
                tbl = agg["hists"].setdefault(mkey, {})
                cur = tbl.get(skey)
                if cur is None:
                    tbl[skey] = {
                        "counts": list(val["counts"]),
                        "sum": val["sum"],
                        "total": val["total"],
                    }
                else:
                    cur["counts"] = [
                        a + b for a, b in zip(cur["counts"], val["counts"])
                    ]
                    cur["sum"] += val["sum"]
                    cur["total"] += val["total"]
            elif m["k"] == GAUGE:
                agg["gauges"].setdefault(mkey, {})[skey] = [float(val), now]
            else:
                tbl = agg["counters"].setdefault(mkey, {})
                tbl[skey] = tbl.get(skey, 0.0) + float(val)


# ---------------------------------------------------------------------------
# Prometheus rendering (dashboard side)
# ---------------------------------------------------------------------------


def _prom_name(mkey: str, kind: str) -> str:
    name = "ray_tpu_" + mkey.replace(".", "_").replace("-", "_")
    if kind == COUNTER and not name.endswith("_total"):
        name += "_total"
    return name


def _label_str(skey: str, extra: str = "") -> str:
    node, _, lkey = skey.partition("|")
    labels = dict(json.loads(lkey)) if lkey else {}
    labels["node"] = node
    parts = [f'{k}="{v}"' for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return ",".join(parts)


def render_runtime_prometheus(
    agg: dict,
    worker_deadline_stats: Optional[dict] = None,
    now: Optional[float] = None,
    stale_after_s: Optional[float] = None,
) -> str:
    """Render the GCS aggregate as Prometheus text.

    ``worker_deadline_stats`` (the GCS's ReportDeadlineStats aggregate)
    is emitted as the same ``ray_tpu_rpc_deadline_*_total`` families under
    ``node="_worker_aggregate"`` — it overlaps the per-node telemetry
    series by construction (both count worker-side enforcement), so sum
    one or the other, not both. Gauges whose source stopped flushing more
    than ``stale_after_s`` ago are dropped instead of served forever.
    """
    now = time.time() if now is None else now
    if stale_after_s is None:
        stale_after_s = config.metrics_stale_after_s
    lines: List[str] = []
    extra_counters: Dict[str, Dict[str, float]] = {}
    if worker_deadline_stats:
        wds = worker_deadline_stats
        for short, v in (
            ("met", wds.get("met", 0)),
            ("shed", wds.get("shed", 0)),
            ("enforced", wds.get("enforced", 0)),
            ("overruns", len(wds.get("overruns", ()))),
        ):
            extra_counters[f"rpc.deadline_{short}"] = {
                "_worker_aggregate|": float(v)
            }

    mkeys = set(agg["meta"]) | set(extra_counters)
    for mkey in sorted(mkeys):
        meta = agg["meta"].get(
            mkey, {"kind": COUNTER, "help": "", "buckets": None}
        )
        kind = meta["kind"]
        pname = _prom_name(mkey, kind)
        if meta.get("help"):
            lines.append(f"# HELP {pname} {meta['help']}")
        lines.append(f"# TYPE {pname} {kind}")
        if kind == HISTOGRAM:
            bounds = meta.get("buckets") or list(LATENCY_BUCKETS_S)
            for skey, h in sorted(agg["hists"].get(mkey, {}).items()):
                base = _label_str(skey)
                cum = 0
                for bound, c in zip(bounds, h["counts"]):
                    cum += c
                    lb = base + ("," if base else "") + f'le="{bound}"'
                    lines.append(f"{pname}_bucket{{{lb}}} {cum}")
                cum += h["counts"][-1]
                lb = base + ("," if base else "") + 'le="+Inf"'
                lines.append(f"{pname}_bucket{{{lb}}} {cum}")
                braces = f"{{{base}}}" if base else ""
                lines.append(f"{pname}_sum{braces} {h['sum']}")
                lines.append(f"{pname}_count{braces} {h['total']}")
        elif kind == GAUGE:
            for skey, (v, ts) in sorted(agg["gauges"].get(mkey, {}).items()):
                if now - ts > stale_after_s:
                    continue
                base = _label_str(skey)
                braces = f"{{{base}}}" if base else ""
                lines.append(f"{pname}{braces} {v}")
        else:
            series = dict(agg["counters"].get(mkey, {}))
            for skey, v in extra_counters.get(mkey, {}).items():
                series[skey] = series.get(skey, 0.0) + v
            for skey, v in sorted(series.items()):
                base = _label_str(skey)
                braces = f"{{{base}}}" if base else ""
                lines.append(f"{pname}{braces} {v}")
    if not lines:
        return ""
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Flight-recorder timeline dump (chaos triage)
# ---------------------------------------------------------------------------


def merged_timeline(*event_lists: List[tuple]) -> List[dict]:
    """Merge per-process event lists into one time-ordered timeline of
    JSON-able dicts."""
    merged: List[tuple] = []
    for evs in event_lists:
        merged.extend(tuple(e) for e in evs)
    merged.sort(key=lambda e: e[0])
    return [
        {"ts": ts, "component": comp, "event": ev, **dict(fields)}
        for ts, comp, ev, fields in merged
    ]


def dump_timeline(path: str, *event_lists: List[tuple]) -> int:
    """Write a merged, time-ordered JSONL timeline; returns event count."""
    timeline = merged_timeline(*event_lists)
    with open(path, "w") as f:
        for entry in timeline:
            f.write(json.dumps(entry) + "\n")
    return len(timeline)
